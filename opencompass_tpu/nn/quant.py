"""Weight-only quantization for the decode-bound eval path.

Decode reads every weight byte once per generated token, so on a v5e the
per-step floor is weight-bytes / HBM bandwidth (measured ~600 GB/s on the
matmul stream).  Storing the transformer matmul weights as int8 (or int4)
with a per-output-channel bf16 scale halves (quarters) those bytes; the
MXU consumes the quantized operand through an on-the-fly convert fused
into the matmul, and the product is rescaled after the contraction (valid
because the scale is constant along the contraction axis).

Quality: symmetric per-channel weight-only int8 is the standard inference
recipe — embeddings, lm_head, norms, and biases stay in bf16.  int4 is
the aggressive storage tier (GPTQ/AWQ-class width; this implementation
keeps per-channel scales).  Activations are quantized only when
``cfg.act_quant`` is on (W8A8: dynamic per-token int8, int8 x int8 on the
MXU — see transformer._dyn_act_quant).

JaxLM exposes the int8 tiers (``quantize='int8'|'w8a8'`` plus
``-kv8``/``-kv4`` cache suffixes) and the packed-int4 tier
(``'w4a8'``): mode ``'int4x2'`` stores two group-quantized int4 values
per uint8 (GROUP=128 contraction groups, NT orientation) and the
nibbles are split *inside* the matmul program
(transformer._packed_matmul) — uint8 crosses the jit boundary fine, so
this sidesteps the TPU plugin's int4-across-jit limitation while the
HBM weight stream stays 4 bits wide.  Plain ``mode='int4'`` (unpacked
int4 arrays) still works on backends whose runtime accepts int4 jit
arguments (CPU does) but is not a JaxLM mode for that reason.

Accuracy ladder: int8/W8A8 is the pinned serving recipe
(QUANT_AGREEMENT_7B.json: decided-item agreement 1.0).  w4a8 is
group-RTN int4 and EXPERIMENTAL: at 7B geometry on random-init weights
its decided-item agreement is 79% and forced-decode agreement 12%
(QUANT_AGREEMENT_7B_W4A8.json) — its value is capacity (13B-class
geometry on one 16 GB chip; weights rest at 4 bits), not fidelity.
Measure your model with ``tools/quant_agreement.py --quant w4a8-kv4``
before trusting scores.
"""
from __future__ import annotations

import functools

import jax.numpy as jnp
import numpy as np

# layer-dict entries that are matmul weights (contraction axis differs by
# storage orientation: q/k/v are (out, in) — see transformer._linear_nt)
_NT_KEYS = ('q', 'k', 'v')
_IN_OUT_KEYS = ('o', 'gate', 'up', 'down', 'fc1', 'fc2')

_QMAX = {'int8': 127.0, 'int4': 7.0, 'int4x2': 7.0}

# int4x2: group size along the contraction axis.  128 matches the MXU
# systolic dim, so the per-group batched contractions still tile cleanly.
GROUP = 128


def _quantize_math(w, axis: int, xp, mode: str, store_dtype=None):
    qmax = _QMAX[mode]
    if store_dtype is None:
        store_dtype = jnp.int4 if mode == 'int4' else xp.int8
    amax = xp.max(xp.abs(w.astype(xp.float32)), axis=axis, keepdims=True)
    scale = xp.maximum(amax / qmax, 1e-12)
    wq = xp.clip(xp.round(w.astype(xp.float32) / scale), -qmax, qmax)
    wq = wq.astype(store_dtype)
    return wq, xp.squeeze(scale, axis=axis).astype(xp.float32)


def _pack_int4x2(w, axis: int, xp):
    """Group-wise int4 quantization packed two-per-uint8.

    The weight is brought to NT orientation (contraction axis LAST) and
    quantized per (output-channel, 128-wide contraction group) to
    [-7, 7]; adjacent contraction pairs pack into one uint8 (low nibble
    = even index).  Returns (packed (..., out, in/2) uint8,
    scales (..., out, in/GROUP) fp32).

    This is the TPU answer to the plugin's int4-across-jit limitation:
    uint8 crosses the jit boundary fine, and the nibbles are split
    inside the matmul program (transformer._packed_matmul), so the HBM
    weight stream — the decode bottleneck — is 4-bit wide while the MXU
    still contracts int8 x int8.

    Stacked (scan-layout) device tensors are packed layer-by-layer via
    ``lax.map``: the pack math makes several fp32-sized temps of its
    input, and doing the whole (L, ...) stack at once inside the fused
    init+quantize program peaks at ~17 GB for a 7B model (measured OOM);
    per-layer sequencing bounds the temps to one layer's worth.
    """
    if xp is jnp and getattr(w, 'ndim', 0) >= 3:
        import jax
        neg = axis if axis < 0 else axis - w.ndim
        return jax.lax.map(lambda wl: _pack_int4x2(wl, neg, xp), w)
    if axis in (-2, w.ndim - 2):           # (in, out) -> NT (out, in)
        w = xp.swapaxes(w, -1, -2)
    K = w.shape[-1]
    if K % GROUP:
        raise ValueError(f'contraction dim {K} not divisible by group '
                         f'{GROUP} (int4x2 mode)')
    wf = w.astype(xp.float32)
    grouped = wf.reshape(*wf.shape[:-1], K // GROUP, GROUP)
    amax = xp.max(xp.abs(grouped), axis=-1, keepdims=True)
    scale = xp.maximum(amax / 7.0, 1e-12)
    q = xp.clip(xp.round(grouped / scale), -7, 7)
    q = q.reshape(wf.shape).astype(xp.int8)
    # split-half pairing: element i shares a byte with element i + K/2,
    # so unpacking is two contiguous nibble-extracts + a concat in
    # natural order — no stride-2 interleave for XLA to materialize
    lo = q[..., :K // 2]
    hi = q[..., K // 2:]
    packed = (lo.astype(xp.uint8) & 0xF) | (hi.astype(xp.uint8) << 4)
    return packed, xp.squeeze(scale, -1).astype(xp.float32)


def _quantize_weight(w, axis: int, mode: str):
    """Symmetric quantization over `axis` (the contraction axis); returns
    (wq, s) with s shaped like w minus that axis.

    Host numpy arrays stay on the host (checkpoint params are quantized
    before sharding so the full model never has to fit one chip; int4
    leaves stay int8-valued on the host and narrow on device transfer).
    Device arrays go through a per-leaf jit; for near-HBM-sized models
    prefer tracing quantize_params together with the initializer in ONE
    program (see models/jax_lm.py) so the full-precision weights only
    ever exist as scheduler temps.
    """
    import jax
    if mode == 'int4x2':
        xp = np if (not isinstance(w, jax.core.Tracer)
                    and not isinstance(w, jax.Array)) else jnp
        return _pack_int4x2(w, axis, xp)
    if isinstance(w, jax.core.Tracer) or not isinstance(w, jax.Array):
        xp = jnp if isinstance(w, jax.core.Tracer) else np
        # numpy has no int4: host copies of int4-mode weights stay
        # int8-valued, and no load path currently narrows them — they
        # keep int8 storage on device (numerically identical, values
        # already clipped to +-7; the int4 memory saving is only realized
        # for device-array/traced inputs, where store_dtype is int4).
        # int4 *weights* are not a shipped JaxLM mode anyway (the axon
        # plugin can't pass int4 across the jit boundary; see
        # models/jax_lm.py quantize validation) — the shipped int4 tier
        # is the KV cache, which is created inside the decode program.
        store = np.int8 if xp is np else None
        return _quantize_math(w, axis, xp, mode, store_dtype=store)
    return _jitted_quantize(axis, mode)(w)


@functools.lru_cache(maxsize=None)
def _jitted_quantize(axis: int, mode: str):
    """One jitted per-leaf quantizer per (axis, mode) — a fresh
    ``jax.jit`` wrapper per call would discard its compile cache and
    retrace every leaf (oct-lint OCT007 caught this)."""
    import jax
    return jax.jit(functools.partial(_quantize_math, axis=axis, xp=jnp,
                                     mode=mode))


def init_packed_params(cfg, key):
    """Random-init parameters DIRECTLY in int4x2 packed form.

    For geometries whose bf16 stack exceeds HBM, the usual fused
    init+quantize program cannot run (a 13B init needs the full ~26 GB
    bf16 stack as the pack's input; measured OOM on a 16 GB v5e) — but
    the packed form itself fits with room to spare.  Random nibbles +
    magnitude-matched scales are statistically the same benchmark
    construct as random bf16 weights, so this is the random-init path
    for ``JaxLM(quantize='w4a8...')`` and the capacity bench legs.
    Real checkpoints never hit this: they quantize host-side in numpy
    and transfer the packed arrays.
    """
    import jax
    if (not cfg.gated_mlp or cfg.qkv_bias or cfg.norm != 'rmsnorm'
            or cfg.parallel_residual or cfg.tie_embeddings):
        raise NotImplementedError(
            'init_packed_params covers the llama-family tree (gated '
            'mlp, rmsnorm, no biases); quantize a real checkpoint '
            'host-side for other families')
    for dim, what in ((cfg.hidden_size, 'hidden_size'),
                      (cfg.intermediate_size, 'intermediate_size'),
                      (cfg.q_dim, 'q_dim')):
        if dim % GROUP:
            # same contract _pack_int4x2 enforces; without this the
            # in_dim // GROUP scale shapes silently collapse to 0
            raise ValueError(
                f'int4x2 packing needs contraction dims divisible by '
                f'{GROUP}; {what}={dim} is not (w4a8 targets 7B/13B-'
                f'class geometries)')
    D, F, L = cfg.hidden_size, cfg.intermediate_size, cfg.num_layers
    V = cfg.vocab_size
    dt = cfg.jnp_dtype

    def bf16(key, shape, scale):
        return (jax.random.normal(key, shape, jnp.float32)
                * scale).astype(dt)

    def packed(key, out_dim, in_dim):
        kw, = jax.random.split(key, 1)
        w = jax.random.randint(kw, (L, out_dim, in_dim // 2), 0, 256,
                               dtype=jnp.int32).astype(jnp.uint8)
        # scale so dequantized std ~ 1/sqrt(in) (init_params' magnitude):
        # uniform nibbles have std ~4.6
        s = jnp.full((L, out_dim, in_dim // GROUP),
                     1.0 / (4.6 * np.sqrt(in_dim)), jnp.bfloat16)
        return {'w': w, 's': s}

    ks = jax.random.split(key, 10)
    layers = {
        'attn_norm': {'scale': jnp.ones((L, D), dt)},
        'mlp_norm': {'scale': jnp.ones((L, D), dt)},
        'q': packed(ks[0], cfg.q_dim, D),
        'k': packed(ks[1], cfg.kv_dim, D),
        'v': packed(ks[2], cfg.kv_dim, D),
        'o': packed(ks[3], D, cfg.q_dim),
        'gate': packed(ks[4], F, D),
        'up': packed(ks[5], F, D),
        'down': packed(ks[6], D, F),
    }
    return {'embed': bf16(ks[7], (V, D), 0.02),
            'layers': layers,
            'final_norm': {'scale': jnp.ones((D,), dt)},
            'lm_head': bf16(ks[8], (D, V), 1.0 / np.sqrt(D))}


def quantize_params(params, cfg, mode: str = 'int8'):
    """Return a copy of `params` with layer matmul weights quantized to
    ``mode`` ('int8', 'int4', or 'int4x2' — packed two-per-uint8 with
    GROUP-wide scales, NT storage; see _pack_int4x2).

    Works on host numpy or device arrays (and traces cleanly under jit);
    leaves everything except the layer matmul 'w' entries untouched.
    Handles both stacked (scan) and per-layer (unrolled list) layouts —
    the contraction axis is counted from the trailing end so a leading
    layer dim never shifts it.
    """
    if mode not in _QMAX:
        raise ValueError(f'unknown quantization mode {mode!r}')

    def quantize_layer(layer):
        out = {}
        for name, p in layer.items():
            if isinstance(p, dict) and 'w' in p and np.ndim(p['w']) >= 2:
                if getattr(p['w'], 'dtype', None) in (
                        jnp.dtype(jnp.int8), jnp.dtype(jnp.int4),
                        jnp.dtype(jnp.uint8)):
                    out[name] = p  # already quantized: keep its scales
                    continue
                axis = -1 if name in _NT_KEYS else -2
                if name in _NT_KEYS or name in _IN_OUT_KEYS:
                    wq, s = _quantize_weight(p['w'], axis, mode)
                    q = dict(p, w=wq, s=s.astype(jnp.bfloat16))
                    out[name] = q
                    continue
            out[name] = p
        return out

    layers = params['layers']
    if isinstance(layers, (list, tuple)):
        new_layers = type(layers)(quantize_layer(lp) for lp in layers)
    else:
        new_layers = quantize_layer(layers)
    return dict(params, layers=new_layers)
