"""Weight-only quantization for the decode-bound eval path.

Decode reads every weight byte once per generated token, so on a v5e the
per-step floor is weight-bytes / HBM bandwidth (measured ~600 GB/s on the
matmul stream).  Storing the transformer matmul weights as int8 (or int4)
with a per-output-channel bf16 scale halves (quarters) those bytes; the
MXU consumes the quantized operand through an on-the-fly convert fused
into the matmul, and the product is rescaled after the contraction (valid
because the scale is constant along the contraction axis).

Quality: symmetric per-channel weight-only int8 is the standard inference
recipe — embeddings, lm_head, norms, and biases stay in bf16.  int4 is
the aggressive storage tier (GPTQ/AWQ-class width; this implementation
keeps per-channel scales).  Activations are quantized only when
``cfg.act_quant`` is on (W8A8: dynamic per-token int8, int8 x int8 on the
MXU — see transformer._dyn_act_quant).

JaxLM exposes the int8 tiers (``quantize='int8'|'w8a8'`` plus
``-kv8``/``-kv4`` cache suffixes).  ``mode='int4'`` weights work at this
API level (useful on backends whose runtime accepts int4 jit arguments —
CPU does) but are not a JaxLM mode: the current TPU plugin cannot pass
int4 arrays across the jit boundary, and model parameters cross it.
"""
from __future__ import annotations

import functools

import jax.numpy as jnp
import numpy as np

# layer-dict entries that are matmul weights (contraction axis differs by
# storage orientation: q/k/v are (out, in) — see transformer._linear_nt)
_NT_KEYS = ('q', 'k', 'v')
_IN_OUT_KEYS = ('o', 'gate', 'up', 'down', 'fc1', 'fc2')

_QMAX = {'int8': 127.0, 'int4': 7.0}


def _quantize_math(w, axis: int, xp, mode: str, store_dtype=None):
    qmax = _QMAX[mode]
    if store_dtype is None:
        store_dtype = jnp.int4 if mode == 'int4' else xp.int8
    amax = xp.max(xp.abs(w.astype(xp.float32)), axis=axis, keepdims=True)
    scale = xp.maximum(amax / qmax, 1e-12)
    wq = xp.clip(xp.round(w.astype(xp.float32) / scale), -qmax, qmax)
    wq = wq.astype(store_dtype)
    return wq, xp.squeeze(scale, axis=axis).astype(xp.float32)


def _quantize_weight(w, axis: int, mode: str):
    """Symmetric quantization over `axis` (the contraction axis); returns
    (wq, s) with s shaped like w minus that axis.

    Host numpy arrays stay on the host (checkpoint params are quantized
    before sharding so the full model never has to fit one chip; int4
    leaves stay int8-valued on the host and narrow on device transfer).
    Device arrays go through a per-leaf jit; for near-HBM-sized models
    prefer tracing quantize_params together with the initializer in ONE
    program (see models/jax_lm.py) so the full-precision weights only
    ever exist as scheduler temps.
    """
    import jax
    if isinstance(w, jax.core.Tracer) or not isinstance(w, jax.Array):
        xp = jnp if isinstance(w, jax.core.Tracer) else np
        # numpy has no int4: host copies of int4-mode weights stay
        # int8-valued, and no load path currently narrows them — they
        # keep int8 storage on device (numerically identical, values
        # already clipped to +-7; the int4 memory saving is only realized
        # for device-array/traced inputs, where store_dtype is int4).
        # int4 *weights* are not a shipped JaxLM mode anyway (the axon
        # plugin can't pass int4 across the jit boundary; see
        # models/jax_lm.py quantize validation) — the shipped int4 tier
        # is the KV cache, which is created inside the decode program.
        store = np.int8 if xp is np else None
        return _quantize_math(w, axis, xp, mode, store_dtype=store)
    return jax.jit(functools.partial(_quantize_math, axis=axis, xp=jnp,
                                     mode=mode))(w)


def quantize_params(params, cfg, mode: str = 'int8'):
    """Return a copy of `params` with layer matmul weights quantized to
    ``mode`` ('int8' or 'int4').

    Works on host numpy or device arrays (and traces cleanly under jit);
    leaves everything except the layer matmul 'w' entries untouched.
    Handles both stacked (scan) and per-layer (unrolled list) layouts —
    the contraction axis is counted from the trailing end so a leading
    layer dim never shifts it.
    """
    if mode not in _QMAX:
        raise ValueError(f'unknown quantization mode {mode!r}')

    def quantize_layer(layer):
        out = {}
        for name, p in layer.items():
            if isinstance(p, dict) and 'w' in p and np.ndim(p['w']) >= 2:
                if getattr(p['w'], 'dtype', None) in (
                        jnp.dtype(jnp.int8), jnp.dtype(jnp.int4)):
                    out[name] = p  # already quantized: keep its scales
                    continue
                axis = -1 if name in _NT_KEYS else -2
                if name in _NT_KEYS or name in _IN_OUT_KEYS:
                    wq, s = _quantize_weight(p['w'], axis, mode)
                    q = dict(p, w=wq, s=s.astype(jnp.bfloat16))
                    out[name] = q
                    continue
            out[name] = p
        return out

    layers = params['layers']
    if isinstance(layers, (list, tuple)):
        new_layers = type(layers)(quantize_layer(lp) for lp in layers)
    else:
        new_layers = quantize_layer(layers)
    return dict(params, layers=new_layers)
