"""Paged KV cache: fixed-size pages in a preallocated pool.

The dense decode cache (``transformer.init_cache``) reserves
``B x (S_bucket + max_new)`` slots per batch — every row pays the
longest row's footprint, and every distinct ``(B, S)`` bucket is its own
buffer (and its own XLA compile of everything that touches it).  The
paged layout breaks that coupling the vLLM/"Ragged Paged Attention" way
(PAPERS.md):

- **Pool**: one preallocated buffer of ``num_pages`` fixed-size pages
  per cache tensor, leaves shaped ``(L, P, K, page, hd)`` (plus
  ``(L, P, K, page)`` per-vector scales for quantized caches).  The
  pool's size is a capacity knob, not a per-batch shape.
- **Page tables**: each in-flight sequence owns an ordered list of page
  ids; its logical KV positions ``[0, len)`` map to
  ``pages[p // page_size]`` at offset ``p % page_size``.  Tables are
  tiny host arrays shipped per step — remapping a slot to a new
  sequence costs an int32 row write, never a cache copy.
- **Alloc/free per row**: the host-side :class:`PageAllocator` hands
  pages out of a free list as rows join the resident decode step and
  reclaims them as rows retire.  Page 0 is reserved as a garbage page:
  inactive slots' writes are routed there, so a scatter can run for the
  full fixed slot set without corrupting live sequences.

Device access patterns (consumed by ``transformer._block``'s paged
branch via :func:`gather_view` / scatter indices from
:func:`write_indices`): reads gather a sequence's pages into a
contiguous head-major view (the XLA-portable formulation of the ragged
paged attention kernel — on TPU a Pallas kernel could read the pages in
place, see docs/user_guides/performance.md), writes scatter one chunk
of tokens into the pages the table names.

Invariants (pinned by tests/test_paged_kv.py): the allocator never
double-books or leaks a page under randomized join/retire orders, and a
paged cache holding the same K/V as a dense cache attends bit-identically.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from .config import TransformerConfig

# reserved garbage page: never allocated, absorbs writes from inactive
# slots and masked chunk tails so one fixed-shape scatter serves the
# whole slot set
GARBAGE_PAGE = 0


class OutOfPages(RuntimeError):
    """The pool has fewer free pages than a joining row needs — callers
    keep the row queued (back-pressure) instead of failing it."""


class PageAllocator:
    """Host-side free list over ``num_pages`` pool pages.

    Page ``GARBAGE_PAGE`` is reserved and never handed out.  ``alloc``
    and ``free`` enforce the no-alias/no-leak invariants directly:
    allocating a page twice or freeing a page not currently allocated
    raises instead of silently corrupting a neighbouring sequence's
    cache.
    """

    def __init__(self, num_pages: int):
        if num_pages < 2:
            raise ValueError('need >= 2 pages (page 0 is reserved)')
        self.num_pages = num_pages
        # the allocator itself is lock-free: every caller mutates it
        # under the engine's state lock (documented, not lexically
        # checkable from this file)
        # guarded-by: external:ContinuousEngine._lock
        self._free: List[int] = list(range(num_pages - 1, 0, -1))
        # guarded-by: external:ContinuousEngine._lock
        self._allocated: set = set()
        # pool-pressure telemetry (obs/costmodel roofline plane): the
        # occupancy high-water mark and how many allocations bounced on
        # an exhausted pool (admission back-pressure) — the two numbers
        # that make an undersized kv_pool_pages visible instead of
        # silently serializing the engine
        self.high_water = 0
        self.failed_allocs = 0

    @property
    def n_free(self) -> int:
        return len(self._free)

    @property
    def n_allocated(self) -> int:
        return len(self._allocated)

    @property
    def usable_pages(self) -> int:
        """Pages the allocator can ever hand out (pool minus the
        reserved garbage page) — the denominator for occupancy/
        high-water fractions."""
        return self.num_pages - 1

    def stats(self) -> dict:
        """Occupancy gauges for heartbeats / status.json / /metrics."""
        usable = max(self.usable_pages, 1)
        return {
            'pages': self.num_pages,
            'used': self.n_allocated,
            'free': self.n_free,
            'used_frac': round(self.n_allocated / usable, 4),
            'high_water': self.high_water,
            'high_water_frac': round(self.high_water / usable, 4),
            'failed_allocs': self.failed_allocs,
        }

    def alloc(self, n: int) -> List[int]:
        """``n`` distinct pages, or :class:`OutOfPages` (atomic: on
        failure nothing is taken; the bounce is counted in
        ``failed_allocs``)."""
        if n > len(self._free):
            self.failed_allocs += 1
            raise OutOfPages(
                f'need {n} pages, {len(self._free)} free '
                f'(pool of {self.num_pages})')
        pages = [self._free.pop() for _ in range(n)]
        for p in pages:
            if p in self._allocated or p == GARBAGE_PAGE:
                raise AssertionError(f'allocator handed out page {p} twice')
            self._allocated.add(p)
        self.high_water = max(self.high_water, len(self._allocated))
        return pages

    def free(self, pages: List[int]):
        for p in pages:
            if p not in self._allocated:
                raise AssertionError(
                    f'freeing page {p} that is not allocated '
                    '(double free or alias)')
            self._allocated.remove(p)
            self._free.append(p)


def pool_pages_for(slots: int, max_len: int, page_size: int) -> int:
    """Default pool size: every slot can hold a full-context sequence,
    plus the reserved garbage page.  Smaller pools are legal and simply
    back-pressure admissions."""
    return slots * pages_per_seq(max_len, page_size) + 1


def pages_per_seq(max_len: int, page_size: int) -> int:
    return -(-int(max_len) // int(page_size))


def init_page_pool(cfg: TransformerConfig, num_pages: int,
                   page_size: int, dtype=None) -> Dict:
    """The pooled cache tensors, same leaf roles as
    ``transformer.init_cache`` but paged: k/v ``(L, P, K, page, hd)``
    (+ ``ks``/``vs`` ``(L, P, K, page)`` per-vector scales when the
    config quantizes its KV cache)."""
    dtype = dtype or cfg.jnp_dtype
    shape = (cfg.num_layers, num_pages, cfg.num_kv_heads, page_size,
             cfg.head_dim)
    mode = cfg.kv_quant_mode
    if mode:
        kv_dtype = jnp.int4 if mode == 'int4' else jnp.int8
        return {'k': jnp.zeros(shape, kv_dtype),
                'v': jnp.zeros(shape, kv_dtype),
                'ks': jnp.ones(shape[:-1], dtype),
                'vs': jnp.ones(shape[:-1], dtype)}
    return {'k': jnp.zeros(shape, dtype), 'v': jnp.zeros(shape, dtype)}


def write_indices(page_table: jnp.ndarray, start: jnp.ndarray,
                  n_new: jnp.ndarray, t: int, page_size: int
                  ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Scatter coordinates for one step of ``t`` tokens per slot.

    Token ``i`` of slot ``s`` lands at logical position
    ``start[s] + i`` → ``(page_rows[s, i], offsets[s, i])``.  Tokens
    past ``n_new[s]`` (chunk padding, inactive slots) are routed to the
    garbage page so the scatter shape stays fixed.
    """
    g = start[:, None] + jnp.arange(t, dtype=start.dtype)[None, :]
    rows = jnp.take_along_axis(page_table, g // page_size, axis=1)
    valid = jnp.arange(t)[None, :] < n_new[:, None]
    rows = jnp.where(valid, rows, GARBAGE_PAGE)
    return rows, g % page_size


def gather_view(pool_leaf: jnp.ndarray, page_table: jnp.ndarray
                ) -> jnp.ndarray:
    """Materialize per-slot contiguous views from one layer's pool leaf.

    ``pool_leaf``: ``(P, K, page, hd)`` (or ``(P, K, page)`` for
    scales); ``page_table``: ``(B, MP)``.  Returns head-major
    ``(B, K, MP*page, hd)`` (or ``(B, K, MP*page)``) — logical position
    ``j`` of slot ``s`` at ``view[s, :, j]``.  Unallocated table
    entries point at the garbage page; their positions are beyond every
    valid attention mask.
    """
    took = jnp.take(pool_leaf, page_table, axis=0)  # (B, MP, K, page[,hd])
    if took.ndim == 5:
        b, mp, k, page, hd = took.shape
        return jnp.transpose(took, (0, 2, 1, 3, 4)).reshape(
            b, k, mp * page, hd)
    b, mp, k, page = took.shape
    return jnp.transpose(took, (0, 2, 1, 3)).reshape(b, k, mp * page)


def dense_equivalent(pool: Dict, page_table: np.ndarray,
                     lengths: np.ndarray) -> Dict:
    """Host-side reference: reassemble each slot's dense
    ``(L, B, K, S, hd)`` cache from the pool + table (test oracle for
    the paged-vs-dense bit-identity invariant).  ``S`` is
    ``MP * page``."""
    out = {}
    page_table = np.asarray(page_table)
    for name, leaf in pool.items():
        leaf = np.asarray(leaf)
        gathered = leaf[:, page_table]       # (L, B, MP, K, page[, hd])
        if gathered.ndim == 6:
            length, b, mp, k, page, hd = gathered.shape
            out[name] = np.transpose(gathered, (0, 1, 3, 2, 4, 5)).reshape(
                length, b, k, mp * page, hd)
        else:
            length, b, mp, k, page = gathered.shape
            out[name] = np.transpose(gathered, (0, 1, 3, 2, 4)).reshape(
                length, b, k, mp * page)
    return out


class PageTable:
    """Host-side page-table rows for a fixed slot set.

    ``table`` is the ``(slots, max_pages)`` int32 array shipped to the
    device each step; unmapped entries hold the garbage page.  The
    engine mutates it only through :meth:`assign` / :meth:`clear`, so
    the allocator and the table can never disagree about ownership.
    """

    def __init__(self, slots: int, max_pages: int):
        self.table = np.full((slots, max_pages), GARBAGE_PAGE, np.int32)
        self._pages: List[Optional[List[int]]] = [None] * slots

    def assign(self, slot: int, pages: List[int]):
        if self._pages[slot] is not None:
            raise AssertionError(f'slot {slot} already mapped')
        if len(pages) > self.table.shape[1]:
            raise ValueError(
                f'{len(pages)} pages exceed table width '
                f'{self.table.shape[1]}')
        self._pages[slot] = list(pages)
        self.table[slot, :] = GARBAGE_PAGE
        self.table[slot, :len(pages)] = pages

    def clear(self, slot: int) -> List[int]:
        """Unmap a slot, returning its pages for the allocator."""
        pages = self._pages[slot]
        if pages is None:
            return []
        self._pages[slot] = None
        self.table[slot, :] = GARBAGE_PAGE
        return pages

    def pages(self, slot: int) -> Optional[List[int]]:
        return self._pages[slot]
