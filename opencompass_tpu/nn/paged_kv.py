"""Paged KV cache: fixed-size pages in a preallocated pool.

The dense decode cache (``transformer.init_cache``) reserves
``B x (S_bucket + max_new)`` slots per batch — every row pays the
longest row's footprint, and every distinct ``(B, S)`` bucket is its own
buffer (and its own XLA compile of everything that touches it).  The
paged layout breaks that coupling the vLLM/"Ragged Paged Attention" way
(PAPERS.md):

- **Pool**: one preallocated buffer of ``num_pages`` fixed-size pages
  per cache tensor, leaves shaped ``(L, P, K, page, hd)`` (plus
  ``(L, P, K, page)`` per-vector scales for quantized caches).  The
  pool's size is a capacity knob, not a per-batch shape.
- **Page tables**: each in-flight sequence owns an ordered list of page
  ids; its logical KV positions ``[0, len)`` map to
  ``pages[p // page_size]`` at offset ``p % page_size``.  Tables are
  tiny host arrays shipped per step — remapping a slot to a new
  sequence costs an int32 row write, never a cache copy.
- **Alloc/free per row**: the host-side :class:`PageAllocator` hands
  pages out of a free list as rows join the resident decode step and
  reclaims them as rows retire.  Page 0 is reserved as a garbage page:
  inactive slots' writes are routed there, so a scatter can run for the
  full fixed slot set without corrupting live sequences.

Device access patterns (consumed by ``transformer._block``'s paged
branch via :func:`gather_view` / scatter indices from
:func:`write_indices`): reads gather a sequence's pages into a
contiguous head-major view (the XLA-portable formulation of the ragged
paged attention kernel — on TPU a Pallas kernel could read the pages in
place, see docs/user_guides/performance.md), writes scatter one chunk
of tokens into the pages the table names.

Invariants (pinned by tests/test_paged_kv.py): the allocator never
double-books or leaks a page under randomized join/retire orders, and a
paged cache holding the same K/V as a dense cache attends bit-identically.

**Prefix sharing** (:class:`RadixPrefixCache`): pages are refcounted, so
one physical page can back the same prompt prefix in several slots' page
tables at once.  A radix-style token trie maps page-granular prompt
chunks to the pool page that already holds their K/V; rows that match
skip prefilling the matched tokens entirely.  The trie holds one
reference per adopted page and each matching row holds another, so
``free()`` at retirement only recycles a page once the last reference
drops — retirement can never corrupt a sibling row mid-decode.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from .config import TransformerConfig

# reserved garbage page: never allocated, absorbs writes from inactive
# slots and masked chunk tails so one fixed-shape scatter serves the
# whole slot set
GARBAGE_PAGE = 0


class OutOfPages(RuntimeError):
    """The pool has fewer free pages than a joining row needs — callers
    keep the row queued (back-pressure) instead of failing it."""


class PageAllocator:
    """Host-side refcounted free list over ``num_pages`` pool pages.

    Page ``GARBAGE_PAGE`` is reserved and never handed out.  ``alloc``
    and ``free`` enforce the no-alias/no-leak invariants directly:
    allocating a page twice or freeing a page not currently allocated
    raises instead of silently corrupting a neighbouring sequence's
    cache.

    Prefix sharing adds reference counts: ``alloc`` hands a page out at
    refcount 1, ``retain`` adds a reference (a trie node or a second
    row adopting the page read-only), and ``free`` only returns a page
    to the free list once its last reference drops.  Occupancy gauges
    (``n_allocated`` / ``stats()``) count *distinct* pages, never
    per-reference — a page shared by five rows is one used page.
    """

    def __init__(self, num_pages: int):
        if num_pages < 2:
            raise ValueError('need >= 2 pages (page 0 is reserved)')
        self.num_pages = num_pages
        # the allocator itself is lock-free: every caller mutates it
        # under the engine's state lock (documented, not lexically
        # checkable from this file)
        # guarded-by: external:ContinuousEngine._lock
        self._free: List[int] = list(range(num_pages - 1, 0, -1))
        # page id -> reference count (>= 1 while allocated)
        # guarded-by: external:ContinuousEngine._lock
        self._refs: Dict[int, int] = {}
        # pool-pressure telemetry (obs/costmodel roofline plane): the
        # occupancy high-water mark and how many allocations bounced on
        # an exhausted pool (admission back-pressure) — the two numbers
        # that make an undersized kv_pool_pages visible instead of
        # silently serializing the engine
        self.high_water = 0
        self.failed_allocs = 0

    @property
    def n_free(self) -> int:
        return len(self._free)

    @property
    def n_allocated(self) -> int:
        """Distinct allocated pages (shared pages count once)."""
        return len(self._refs)

    @property
    def n_shared(self) -> int:
        """Pages currently held by more than one reference."""
        return sum(1 for c in self._refs.values() if c > 1)

    @property
    def usable_pages(self) -> int:
        """Pages the allocator can ever hand out (pool minus the
        reserved garbage page) — the denominator for occupancy/
        high-water fractions."""
        return self.num_pages - 1

    def stats(self) -> dict:
        """Occupancy gauges for heartbeats / status.json / /metrics."""
        usable = max(self.usable_pages, 1)
        return {
            'pages': self.num_pages,
            'used': self.n_allocated,
            'free': self.n_free,
            'shared': self.n_shared,
            'used_frac': round(self.n_allocated / usable, 4),
            'high_water': self.high_water,
            'high_water_frac': round(self.high_water / usable, 4),
            'failed_allocs': self.failed_allocs,
        }

    def alloc(self, n: int) -> List[int]:
        """``n`` distinct pages at refcount 1, or :class:`OutOfPages`
        (atomic: on failure nothing is taken; the bounce is counted in
        ``failed_allocs``)."""
        if n > len(self._free):
            self.failed_allocs += 1
            raise OutOfPages(
                f'need {n} pages, {len(self._free)} free '
                f'(pool of {self.num_pages})')
        pages = [self._free.pop() for _ in range(n)]
        for p in pages:
            if p in self._refs or p == GARBAGE_PAGE:
                raise AssertionError(f'allocator handed out page {p} twice')
            self._refs[p] = 1
        self.high_water = max(self.high_water, len(self._refs))
        return pages

    def retain(self, pages: List[int]):
        """Add one reference to each (already allocated) page — a trie
        node adopting it, or a row mapping it read-only into its slot."""
        for p in pages:
            if p not in self._refs:
                raise AssertionError(
                    f'retaining page {p} that is not allocated')
            self._refs[p] += 1

    def refcount(self, page: int) -> int:
        return self._refs.get(page, 0)

    def free(self, pages: List[int]):
        """Drop one reference per page; a page returns to the free list
        only when its last reference drops."""
        for p in pages:
            if p not in self._refs:
                raise AssertionError(
                    f'freeing page {p} that is not allocated '
                    '(double free or alias)')
            self._refs[p] -= 1
            if self._refs[p] == 0:
                del self._refs[p]
                self._free.append(p)


def pool_pages_for(slots: int, max_len: int, page_size: int) -> int:
    """Default pool size: every slot can hold a full-context sequence,
    plus the reserved garbage page.  Smaller pools are legal and simply
    back-pressure admissions."""
    return slots * pages_per_seq(max_len, page_size) + 1


def pages_per_seq(max_len: int, page_size: int) -> int:
    return -(-int(max_len) // int(page_size))


def init_page_pool(cfg: TransformerConfig, num_pages: int,
                   page_size: int, dtype=None) -> Dict:
    """The pooled cache tensors, same leaf roles as
    ``transformer.init_cache`` but paged: k/v ``(L, P, K, page, hd)``
    (+ ``ks``/``vs`` ``(L, P, K, page)`` per-vector scales when the
    config quantizes its KV cache)."""
    dtype = dtype or cfg.jnp_dtype
    shape = (cfg.num_layers, num_pages, cfg.num_kv_heads, page_size,
             cfg.head_dim)
    mode = cfg.kv_quant_mode
    if mode:
        kv_dtype = jnp.int4 if mode == 'int4' else jnp.int8
        return {'k': jnp.zeros(shape, kv_dtype),
                'v': jnp.zeros(shape, kv_dtype),
                'ks': jnp.ones(shape[:-1], dtype),
                'vs': jnp.ones(shape[:-1], dtype)}
    return {'k': jnp.zeros(shape, dtype), 'v': jnp.zeros(shape, dtype)}


def write_indices(page_table: jnp.ndarray, start: jnp.ndarray,
                  n_new: jnp.ndarray, t: int, page_size: int
                  ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Scatter coordinates for one step of ``t`` tokens per slot.

    Token ``i`` of slot ``s`` lands at logical position
    ``start[s] + i`` → ``(page_rows[s, i], offsets[s, i])``.  Tokens
    past ``n_new[s]`` (chunk padding, inactive slots) are routed to the
    garbage page so the scatter shape stays fixed.
    """
    g = start[:, None] + jnp.arange(t, dtype=start.dtype)[None, :]
    rows = jnp.take_along_axis(page_table, g // page_size, axis=1)
    valid = jnp.arange(t)[None, :] < n_new[:, None]
    rows = jnp.where(valid, rows, GARBAGE_PAGE)
    return rows, g % page_size


def gather_view(pool_leaf: jnp.ndarray, page_table: jnp.ndarray
                ) -> jnp.ndarray:
    """Materialize per-slot contiguous views from one layer's pool leaf.

    ``pool_leaf``: ``(P, K, page, hd)`` (or ``(P, K, page)`` for
    scales); ``page_table``: ``(B, MP)``.  Returns head-major
    ``(B, K, MP*page, hd)`` (or ``(B, K, MP*page)``) — logical position
    ``j`` of slot ``s`` at ``view[s, :, j]``.  Unallocated table
    entries point at the garbage page; their positions are beyond every
    valid attention mask.
    """
    took = jnp.take(pool_leaf, page_table, axis=0)  # (B, MP, K, page[,hd])
    if took.ndim == 5:
        b, mp, k, page, hd = took.shape
        return jnp.transpose(took, (0, 2, 1, 3, 4)).reshape(
            b, k, mp * page, hd)
    b, mp, k, page = took.shape
    return jnp.transpose(took, (0, 2, 1, 3)).reshape(b, k, mp * page)


def dense_equivalent(pool: Dict, page_table: np.ndarray,
                     lengths: np.ndarray) -> Dict:
    """Host-side reference: reassemble each slot's dense
    ``(L, B, K, S, hd)`` cache from the pool + table (test oracle for
    the paged-vs-dense bit-identity invariant).  ``S`` is
    ``MP * page``."""
    out = {}
    page_table = np.asarray(page_table)
    for name, leaf in pool.items():
        leaf = np.asarray(leaf)
        gathered = leaf[:, page_table]       # (L, B, MP, K, page[, hd])
        if gathered.ndim == 6:
            length, b, mp, k, page, hd = gathered.shape
            out[name] = np.transpose(gathered, (0, 1, 3, 2, 4, 5)).reshape(
                length, b, k, mp * page, hd)
        else:
            length, b, mp, k, page = gathered.shape
            out[name] = np.transpose(gathered, (0, 1, 3, 2, 4)).reshape(
                length, b, k, mp * page)
    return out


class PageTable:
    """Host-side page-table rows for a fixed slot set.

    ``table`` is the ``(slots, max_pages)`` int32 array shipped to the
    device each step; unmapped entries hold the garbage page.  The
    engine mutates it only through :meth:`assign` / :meth:`clear`, so
    the allocator and the table can never disagree about ownership.
    """

    def __init__(self, slots: int, max_pages: int):
        self.table = np.full((slots, max_pages), GARBAGE_PAGE, np.int32)
        self._pages: List[Optional[List[int]]] = [None] * slots

    def assign(self, slot: int, pages: List[int]):
        if self._pages[slot] is not None:
            raise AssertionError(f'slot {slot} already mapped')
        if len(pages) > self.table.shape[1]:
            raise ValueError(
                f'{len(pages)} pages exceed table width '
                f'{self.table.shape[1]}')
        self._pages[slot] = list(pages)
        self.table[slot, :] = GARBAGE_PAGE
        self.table[slot, :len(pages)] = pages

    def clear(self, slot: int) -> List[int]:
        """Unmap a slot, returning its pages for the allocator."""
        pages = self._pages[slot]
        if pages is None:
            return []
        self._pages[slot] = None
        self.table[slot, :] = GARBAGE_PAGE
        return pages

    def pages(self, slot: int) -> Optional[List[int]]:
        return self._pages[slot]


class _TrieNode:
    """One page-granular chunk of a cached prompt prefix.

    ``chunk`` is the ``page_size``-token tuple that keys this node under
    its parent, ``page`` the pool page holding that chunk's K/V (the
    trie owns one allocator reference to it), ``tick`` the LRU stamp,
    ``pinned`` an eviction shield for hot shared prefixes (system
    prompts the serve front door sees repeatedly).
    """

    __slots__ = ('chunk', 'page', 'children', 'parent', 'tick', 'pinned')

    def __init__(self, chunk: Tuple[int, ...], page: int,
                 parent: Optional['_TrieNode'], tick: int):
        self.chunk = chunk
        self.page = page
        self.children: Dict[Tuple[int, ...], '_TrieNode'] = {}
        self.parent = parent
        self.tick = tick
        self.pinned = False


class RadixPrefixCache:
    """Radix-style prefix cache over the refcounted page pool.

    A token trie at page granularity: each node is one full
    ``page_size``-token prompt chunk mapped to the pool page that
    already holds its K/V.  The vLLM-style contract (PAPERS.md):

    - ``match(ids)`` walks the trie along a new prompt and returns the
      longest chain of already-cached full pages (each retained once
      for the calling row, so retirement elsewhere cannot recycle
      them), plus an optional *partial* continuation — a cached page
      whose chunk shares at least ``min_partial`` leading tokens with
      the prompt's next chunk.  The caller copies that page
      (copy-on-write) before its first divergent write lands in it.
    - ``insert(ids, pages)`` adopts the full-prompt pages of a row that
      just finished prefill; pages already present are skipped (the
      row keeps its own references either way), new tail pages gain a
      trie reference.
    - ``evict(n)`` frees least-recently-used leaf pages whose only
      remaining reference is the trie's own — shared pages and interior
      nodes are never touched — so pool pressure reclaims cold prefixes
      instead of bouncing admissions.

    The cache is keyed by ``key`` — ``(model identity, tokenizer
    digest, sampling-relevant params)`` — and lives exactly as long as
    one :class:`~opencompass_tpu.models.jax_lm.ContinuousEngine`
    (which is itself rebuilt whenever any of those change), so a trie
    can never serve K/V computed under different weights, tokenization
    or sampling geometry.  All methods run under the engine's state
    lock, like the allocator they mutate.
    """

    def __init__(self, alloc: PageAllocator, page_size: int,
                 key: Optional[tuple] = None,
                 min_partial: Optional[int] = None):
        self.alloc = alloc
        self.page_size = int(page_size)
        self.key = key
        # a partial (copy-on-write) match must save at least this many
        # prefill tokens to be worth one page alloc + device copy
        self.min_partial = (max(1, self.page_size // 4)
                           if min_partial is None else int(min_partial))
        # guarded-by: external:ContinuousEngine._lock
        self._root: Dict[Tuple[int, ...], _TrieNode] = {}
        # guarded-by: external:ContinuousEngine._lock
        self._tick = 0
        # lifetime gauges (distinct from the engine's per-drain deltas)
        self.nodes = 0
        self.hits = 0
        self.misses = 0
        self.matched_tokens = 0
        self.inserted_pages = 0
        self.evicted_pages = 0
        self.pinned_nodes = 0

    def match(self, ids) -> Tuple[List[int], int, Optional[int]]:
        """Longest cached prefix of ``ids``.

        Returns ``(pages, n_tokens, cow_src)``: ``pages`` the
        fully-matched pool pages in prompt order, ``n_tokens`` the
        total matched token count (full pages plus any partial match
        inside ``cow_src``), and ``cow_src`` the page to copy-on-write
        from (or None).  Every returned page — including ``cow_src`` —
        is retained once for the caller, who must ``free`` them all
        exactly once (for ``cow_src``: right after the copy).

        At least one suffix token is always left unmatched so the
        row's final prefill chunk can produce its first-token logits.
        """
        ps = self.page_size
        self._tick += 1
        ids = list(ids)
        limit = len(ids) - 1
        pages: List[int] = []
        children = self._root
        pos = 0
        while pos + ps <= limit:
            node = children.get(tuple(ids[pos:pos + ps]))
            if node is None:
                break
            node.tick = self._tick
            pages.append(node.page)
            children = node.children
            pos += ps
        # partial continuation: best common-prefix overlap between the
        # prompt's next (incomplete) chunk and any cached child chunk
        cow_src = None
        best_len = 0
        rem = ids[pos:limit]
        if rem:
            for chunk, node in children.items():
                n = 0
                for a, b in zip(chunk, rem):
                    if a != b:
                        break
                    n += 1
                if n > best_len:
                    best_len, cow_src = n, node.page
        if best_len < self.min_partial:
            cow_src, best_len = None, 0
        matched = pos + best_len
        if matched:
            self.hits += 1
            self.matched_tokens += matched
            self.alloc.retain(pages)
            if cow_src is not None:
                self.alloc.retain([cow_src])
        else:
            self.misses += 1
        return pages, matched, cow_src

    def insert(self, ids, pages: List[int]) -> int:
        """Adopt the full-page prompt chunks of a freshly prefilled row.

        ``pages`` is the row's page-table row (prompt pages first).
        Chunks already in the trie are skipped; each newly adopted page
        gains one trie reference.  Returns the number of pages adopted.
        """
        ps = self.page_size
        self._tick += 1
        ids = list(ids)
        adopted = 0
        children = self._root
        parent: Optional[_TrieNode] = None
        for i in range(len(ids) // ps):
            chunk = tuple(ids[i * ps:(i + 1) * ps])
            node = children.get(chunk)
            if node is None:
                page = pages[i]
                self.alloc.retain([page])
                node = _TrieNode(chunk, page, parent, self._tick)
                children[chunk] = node
                self.nodes += 1
                self.inserted_pages += 1
                adopted += 1
            else:
                node.tick = self._tick
            parent = node
            children = node.children
        return adopted

    def _chain(self, ids) -> List[_TrieNode]:
        """The trie nodes covering ``ids``' full-page chunks, longest
        cached run first-to-last (empty when nothing is cached)."""
        ps = self.page_size
        ids = list(ids)
        out: List[_TrieNode] = []
        children = self._root
        for i in range(len(ids) // ps):
            node = children.get(tuple(ids[i * ps:(i + 1) * ps]))
            if node is None:
                break
            out.append(node)
            children = node.children
        return out

    def pin(self, ids) -> int:
        """Shield ``ids``' cached full-page chain from LRU eviction.

        Hot shared prefixes (system prompts the front door sees over
        and over) stay resident under pool pressure; everything else
        still churns.  Idempotent; pages not yet in the trie are
        simply not pinned (call again after the next insert).  Returns
        the number of newly pinned nodes.
        """
        pinned = 0
        for node in self._chain(ids):
            if not node.pinned:
                node.pinned = True
                self.pinned_nodes += 1
                pinned += 1
        return pinned

    def unpin(self, ids) -> int:
        """Release the eviction shield on ``ids``' cached chain.
        Idempotent; returns the number of nodes unpinned."""
        unpinned = 0
        for node in self._chain(ids):
            if node.pinned:
                node.pinned = False
                self.pinned_nodes -= 1
                unpinned += 1
        return unpinned

    def evict(self, n_pages: int) -> int:
        """Free up to ``n_pages`` cold trie pages, LRU leaves first.

        Only pages whose *sole* remaining reference is the trie's own
        are eligible — anything a live row still maps stays put, and
        pinned nodes (plus their ancestors, by construction) are
        skipped.
        Evicting a leaf can expose its parent, so sweep until satisfied
        or nothing is evictable.  Returns the number of pages freed.
        """
        freed = 0
        while freed < n_pages:
            leaves = [n for n in self._iter_nodes()
                      if not n.children and not n.pinned
                      and self.alloc.refcount(n.page) == 1]
            if not leaves:
                break
            leaves.sort(key=lambda n: n.tick)
            for node in leaves:
                if freed >= n_pages:
                    break
                siblings = (node.parent.children if node.parent is not None
                            else self._root)
                del siblings[node.chunk]
                self.alloc.free([node.page])
                self.nodes -= 1
                self.evicted_pages += 1
                freed += 1
        return freed

    def drop_all(self) -> int:
        """Release every trie reference (engine teardown / tests).
        Pages shared with live rows survive until those rows retire."""
        nodes = list(self._iter_nodes())
        for node in nodes:
            self.alloc.free([node.page])
        self._root = {}
        self.nodes = 0
        self.pinned_nodes = 0
        return len(nodes)

    def _iter_nodes(self):
        stack = list(self._root.values())
        while stack:
            node = stack.pop()
            yield node
            stack.extend(node.children.values())

    def stats(self) -> dict:
        """Lifetime trie gauges for engine stats / heartbeats."""
        return {
            'nodes': self.nodes,
            'hits': self.hits,
            'misses': self.misses,
            'matched_tokens': self.matched_tokens,
            'inserted_pages': self.inserted_pages,
            'evicted_pages': self.evicted_pages,
            'pinned_nodes': self.pinned_nodes,
        }
