"""OpenAI-compatible chat API model.

Parity: reference opencompass/models/openai_api.py:13-155 — ThreadPoolExecutor
fan-out, HUMAN/BOT/SYSTEM → user/assistant/system role mapping, retry on
rate-limit with token-bucket pacing, tiktoken-or-heuristic token counting.
Implemented over ``urllib`` so any OpenAI-compatible endpoint (vLLM, llama
server, proxies) works without the openai SDK; zero-egress environments get
a clean error only at call time.
"""
from __future__ import annotations

import os
from concurrent.futures import ThreadPoolExecutor
from typing import Dict, List, Optional, Union

from opencompass_tpu.registry import MODELS
from opencompass_tpu.utils.logging import get_logger
from opencompass_tpu.utils.prompt import PromptList

from .base_api import BaseAPIModel

logger = get_logger()

PromptType = Union[PromptList, str]

OPENAI_API_BASE = os.environ.get(
    'OPENAI_API_BASE', 'https://api.openai.com/v1/chat/completions')


@MODELS.register_module()
class OpenAI(BaseAPIModel):
    """Args:
        path: model name (e.g. 'gpt-4').
        key: API key, or 'ENV' to read OPENAI_API_KEY.
        max_out_len / temperature: generation defaults.
        openai_api_base: endpoint URL (any OpenAI-compatible server).
    """

    is_api = True

    def __init__(self,
                 path: str = 'gpt-3.5-turbo',
                 max_seq_len: int = 2048,
                 query_per_second: int = 1,
                 retry: int = 2,
                 key: str = 'ENV',
                 meta_template: Optional[Dict] = None,
                 openai_api_base: str = OPENAI_API_BASE,
                 temperature: Optional[float] = None,
                 generation_kwargs: Optional[Dict] = None):
        super().__init__(path=path,
                         max_seq_len=max_seq_len,
                         meta_template=meta_template,
                         query_per_second=query_per_second,
                         retry=retry,
                         generation_kwargs=generation_kwargs)
        self.temperature = temperature
        self.key = os.environ.get('OPENAI_API_KEY', '') if key == 'ENV' \
            else key
        self.url = openai_api_base

    def generate(self, inputs: List[PromptType],
                 max_out_len: int = 512) -> List[str]:
        with ThreadPoolExecutor() as executor:
            futures = [executor.submit(self._generate, p, max_out_len)
                       for p in inputs]
            try:
                return [f.result() for f in futures]
            except Exception:
                # fail fast: a dead endpoint must not burn the full retry
                # budget on every queued prompt before the task fails
                for f in futures:
                    f.cancel()
                raise

    def _to_messages(self, prompt: PromptType) -> List[Dict]:
        if isinstance(prompt, str):
            return [{'role': 'user', 'content': prompt}]
        role_map = {'HUMAN': 'user', 'BOT': 'assistant', 'SYSTEM': 'system'}
        return [{
            'role': role_map.get(item['role'], 'user'),
            'content': item['prompt'],
        } for item in prompt]

    def _generate(self, prompt: PromptType, max_out_len: int) -> str:
        messages = self._to_messages(prompt)
        body = {
            'model': self.path,
            'messages': messages,
            'max_tokens': max_out_len,
        }
        if self.temperature is not None:
            body['temperature'] = self.temperature
        body.update(self.generation_kwargs)

        # shared transport (base_api.post_json): rate limiting, 429
        # backoff, 4xx fast-fail, exception chaining.  A failure raises so
        # the task fails rather than scoring empty predictions as wrong
        # answers (reference models/openai_api.py raises after its budget).
        data = self.post_json(
            self.url, body,
            headers={'Authorization': f'Bearer {self.key}'}, timeout=60)
        return data['choices'][0]['message']['content'].strip()

    def get_token_len(self, prompt: str) -> int:
        try:
            import tiktoken
            enc = tiktoken.encoding_for_model(self.path)
            return len(enc.encode(prompt))
        except Exception:
            return super().get_token_len(prompt)
