"""OpenAI-compatible chat API model.

Parity: reference opencompass/models/openai_api.py:13-155 —
HUMAN/BOT/SYSTEM → user/assistant/system role mapping, retry on
rate-limit, tiktoken-or-heuristic token counting.  Implemented over
``urllib`` so any OpenAI-compatible endpoint (vLLM, llama server,
proxies) works without the openai SDK; zero-egress environments get a
clean error only at call time.

Concurrency is the outbound scheduler's, not a per-call
``ThreadPoolExecutor``: rows fan out under an AIMD in-flight window
with ``Retry-After``-honoring pacing, budgeted jittered retries, a
per-provider circuit breaker, and typed per-row partial failures
(docs/user_guides/api_models.md).
"""
from __future__ import annotations

import os
from typing import Dict, List, Optional, Union

from opencompass_tpu.registry import MODELS
from opencompass_tpu.utils.logging import get_logger
from opencompass_tpu.utils.prompt import PromptList

from .base_api import BaseAPIModel

logger = get_logger()

PromptType = Union[PromptList, str]

OPENAI_API_BASE = os.environ.get(
    'OPENAI_API_BASE', 'https://api.openai.com/v1/chat/completions')


@MODELS.register_module()
class OpenAI(BaseAPIModel):
    """Args:
        path: model name (e.g. 'gpt-4').
        key: API key, or 'ENV' to read OPENAI_API_KEY.
        max_out_len / temperature: generation defaults.
        openai_api_base: endpoint URL (any OpenAI-compatible server).
    """

    is_api = True

    def __init__(self,
                 path: str = 'gpt-3.5-turbo',
                 max_seq_len: int = 2048,
                 query_per_second: int = 1,
                 retry: int = 2,
                 key: str = 'ENV',
                 meta_template: Optional[Dict] = None,
                 openai_api_base: str = OPENAI_API_BASE,
                 temperature: Optional[float] = None,
                 generation_kwargs: Optional[Dict] = None,
                 max_inflight: int = 8,
                 hedge_after_s: Optional[float] = None,
                 outbound: Optional[Dict] = None):
        super().__init__(path=path,
                         max_seq_len=max_seq_len,
                         meta_template=meta_template,
                         query_per_second=query_per_second,
                         retry=retry,
                         generation_kwargs=generation_kwargs,
                         max_inflight=max_inflight,
                         hedge_after_s=hedge_after_s,
                         outbound=outbound)
        self.temperature = temperature
        self.key = os.environ.get('OPENAI_API_KEY', '') if key == 'ENV' \
            else key
        self.url = openai_api_base

    # generate() is BaseAPIModel's: rows fan out through the outbound
    # scheduler (bounded AIMD in-flight window, budgeted jittered
    # retries, breaker routing).  On a non-retryable rejection — dead
    # key, bad endpoint — the scheduler stops admitting queued siblings
    # and drains the in-flight ones, so a dead endpoint can't burn the
    # full retry budget row by row or leak request threads past the
    # call; completed rows survive as typed partial-failure state.

    def _to_messages(self, prompt: PromptType) -> List[Dict]:
        if isinstance(prompt, str):
            return [{'role': 'user', 'content': prompt}]
        role_map = {'HUMAN': 'user', 'BOT': 'assistant', 'SYSTEM': 'system'}
        return [{
            'role': role_map.get(item['role'], 'user'),
            'content': item['prompt'],
        } for item in prompt]

    def _generate_one(self, prompt: PromptType, max_out_len: int,
                      timeout: float = 60.0) -> str:
        """ONE un-retried chat-completion attempt (the outbound
        scheduler's transport hook).  A failure raises typed so the
        scheduler's policy table decides retry/backoff/breaker — and
        so the task fails rather than scoring empty predictions as
        wrong answers (reference models/openai_api.py raises after its
        budget)."""
        messages = self._to_messages(prompt)
        body = {
            'model': self.path,
            'messages': messages,
            'max_tokens': max_out_len,
        }
        if self.temperature is not None:
            body['temperature'] = self.temperature
        body.update(self.generation_kwargs)
        data = self.post_json_once(
            self.url, body,
            headers={'Authorization': f'Bearer {self.key}'},
            timeout=timeout)
        return data['choices'][0]['message']['content'].strip()

    def get_token_len(self, prompt: str) -> int:
        try:
            import tiktoken
            enc = tiktoken.encoding_for_model(self.path)
            return len(enc.encode(prompt))
        except Exception:
            return super().get_token_len(prompt)
