"""GLM-130B-family model wrapper (parity: reference opencompass/models/
glm.py:16-407, which drives the external GLM-130B/SwissArmyTransformer
package over 8 GPUs with --model-parallel-size 8).

TPU-native design: no external package — the GLM architecture runs on the
in-repo JAX transformer stack with ``prefix_lm`` attention (bidirectional
context, causal answer; nn/transformer.py), tensor-parallel over the mesh
``model`` axis instead of SAT's megatron groups.  The reference wrapper's
three measurement APIs map to:

- ``choice(inputs, choices)`` — conditional log prob of each choice's full
  token sequence given the bidirectional context (reference glm.py:132-164);
  inherited from BaseModel.choice, which routes through the prefix-aware
  ``get_ppl``.
- ``get_ppl`` — forward + shifted CE with the context masked out and
  attended bidirectionally (reference glm.py:380-406 builds the same
  context/answer split via GLM attention masks by hand).
- ``generate`` — the reference fills a [MASK]/[gMASK] span with a
  left-to-right strategy (glm.py:166-285); here the prompt is the
  bidirectional prefix and decode proceeds causally from its end, which is
  exactly the [gMASK] (generation-mask-at-end) path — the only one the
  reference's eval configs use.
"""
from __future__ import annotations

from typing import Dict, Optional, Union

from opencompass_tpu.registry import MODELS

from .jax_lm import JaxLM


@MODELS.register_module()
class GLM130B(JaxLM):
    """Args mirror JaxLM; ``config`` defaults to the GLM-130B preset and
    ``parallel`` to 8-way tensor parallelism (the reference's
    --model-parallel-size 8, reference glm.py:74)."""

    def __init__(self,
                 path: str = '',
                 max_seq_len: int = 2048,
                 config: Union[str, Dict, None] = None,
                 parallel: Optional[Dict] = None,
                 **kwargs):
        if config is None:
            config = 'glm130b'
        elif isinstance(config, dict) and 'preset' not in config:
            config = dict(config, preset='glm130b')
        if parallel is None:
            parallel = dict(data=1, model=8, seq=1)
        super().__init__(path=path, max_seq_len=max_seq_len, config=config,
                         parallel=parallel, **kwargs)
