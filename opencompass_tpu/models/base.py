"""Model abstraction + meta-template parsing.

A model exposes three measurement primitives consumed by the inferencers:
``generate`` (free-form completion), ``get_ppl`` (per-sequence perplexity with
optional context masking) and ``get_token_len``.  Before any of those run, the
structured prompt IR (:class:`~opencompass_tpu.utils.prompt.PromptList`) is
folded through the model's **meta template** — per-role begin/end decorations,
with ``generate: True`` marking where generation starts (gen-mode parsing
truncates the prompt there so the model completes the assistant turn).

Behavioral parity: reference opencompass/models/base.py:10-394 (BaseModel,
LMTemplateParser).  The section/round walking logic is shared with the API
parser via :class:`MetaTemplateWalker` instead of being duplicated.
"""
from __future__ import annotations

import abc
from collections import deque
from copy import deepcopy
from typing import Dict, List, Optional, Tuple, Union

from opencompass_tpu.utils.perf import PerfCounters
from opencompass_tpu.utils.prompt import PromptList

PromptType = Union[PromptList, str]


class _Ready:
    """A completed async result: the sync fallback for models without a
    real dispatch/fetch split.  Intentionally duplicates the scheduler's
    ``ReadyHandle`` (icl/inferencers/schedule.py) rather than importing
    it — the handle contract is duck-typed (``.result()`` only) precisely
    so the model and inferencer layers stay import-decoupled; keep edits
    to either copy in sync."""
    __slots__ = ('_value',)

    def __init__(self, value):
        self._value = value

    def result(self):
        return self._value


class _Lazy:
    """An in-flight async result: ``result()`` blocks on the deferred
    fetch once and caches (accelerator models wrap their host fetch —
    ``np.asarray`` + decode — in one of these)."""
    __slots__ = ('_fetch', '_value', '_done')

    def __init__(self, fetch):
        self._fetch = fetch
        self._done = False
        self._value = None

    def result(self):
        if not self._done:
            self._value = self._fetch()
            self._done = True
            self._fetch = None  # drop closed-over device arrays
        return self._value


class MetaTemplateWalker:
    """Shared machinery for walking a PromptList against a meta template.

    A meta template is ``dict(round=[role dicts...], begin=..., end=...,
    reserved_roles=[...], eos_token_id=...)``.  Subclasses override the three
    ``emit_*`` hooks to produce either a flat string (LM models) or a chat
    message list (API models).
    """

    def __init__(self, meta_template: Optional[Dict] = None):
        self.meta_template = meta_template
        self.roles: Dict[str, dict] = {}
        if meta_template:
            assert 'round' in meta_template, \
                'meta template requires a "round" key'
            assert isinstance(meta_template['round'], list)
            sources = [meta_template['round']]
            if 'reserved_roles' in meta_template:
                assert isinstance(meta_template['reserved_roles'], list)
                sources.append(meta_template['reserved_roles'])
            for source in sources:
                for item in source:
                    if isinstance(item, dict):
                        if item['role'] in self.roles:
                            raise ValueError(
                                f'duplicate role {item["role"]} in meta '
                                'template')
                        self.roles[item['role']] = dict(item)

    # -- hooks -------------------------------------------------------------

    def _role_config(self, role_prompt: Dict) -> Dict:
        """Role config for an IR item, merged with the item's own fields."""
        role = role_prompt.get('role')
        if role not in self.roles:
            role = role_prompt.get('fallback_role')
        if role not in self.roles:
            raise KeyError(f'{role_prompt} has neither a known role nor a '
                           'fallback role')
        merged = dict(self.roles[role])
        merged.update(role_prompt)
        return merged

    def _split_rounds(self, dialogue: List) -> List[int]:
        """Index ranges of dialogue rounds: a new round starts whenever the
        role order wraps around relative to the meta round template."""
        role_order = {
            cfg['role']: i
            for i, cfg in enumerate(self.meta_template['round'])
            if isinstance(cfg, dict)
        }
        last = -1
        cuts = [0]
        for idx, item in enumerate(dialogue):
            if isinstance(item, str):
                continue
            pos = role_order.get(item.get('role'))
            if pos is None:
                pos = role_order.get(item.get('fallback_role'))
                if pos is None:
                    raise KeyError(f'{item} has neither a known role nor a '
                                   'fallback role')
            if pos <= last:
                cuts.append(idx)
            last = pos
        cuts.append(len(dialogue))
        return cuts

    def _updated_roles(self, round_template) -> Dict[str, Dict]:
        """Per-round role dict: defaults overridden by this round's items."""
        role_dict = deepcopy(self.roles)
        items = round_template
        if isinstance(round_template, dict):
            items = [round_template]
        elif isinstance(round_template, str):
            items = []
        for item in items:
            if not isinstance(item, dict):
                continue
            role = item.get('role')
            if role not in self.roles:
                role = item.get('fallback_role')
            if role in role_dict:
                role_dict[role].update(item)
        return role_dict

    def walk(self, prompt_template: PromptList, mode: str):
        """Yield (kind, payload) events: ``('str', s)``, ``('role', (item,
        role_dict, for_gen))`` for begin/end-section items, or ``('round',
        (round_items, role_dict, for_gen))`` for each dialogue round.  The
        consumer decides when to stop (gen-mode truncation)."""
        section_stack: List[Tuple[str, int]] = []
        for i, item in enumerate(prompt_template):
            if isinstance(item, str):
                yield ('str', item)
            elif isinstance(item, dict) and 'section' in item:
                if item['pos'] == 'begin':
                    assert item['section'] in ('begin', 'round', 'end', 'ice')
                    section_stack.append((item['section'], i + 1))
                elif item['pos'] == 'end':
                    section_name, start = section_stack.pop()
                    assert section_name == item['section']
                    if section_name in ('round', 'ice'):
                        dialogue = prompt_template[start:i]
                        cuts = self._split_rounds(dialogue)
                        for r in range(len(cuts) - 1):
                            round_items = dialogue[cuts[r]:cuts[r + 1]]
                            for_gen = (mode == 'gen'
                                       and section_name == 'round'
                                       and r == len(cuts) - 2)
                            yield ('round',
                                   (self.meta_template['round'],
                                    self._updated_roles(round_items), for_gen))
                else:
                    raise ValueError(f'invalid section pos {item["pos"]}')
            elif section_stack and section_stack[-1][0] in ('begin', 'end'):
                yield ('role', (item, self._updated_roles(item), mode == 'gen'))


def _flatten_without_meta(prompt_template) -> str:
    """No-meta-template fallback: join strings and role prompts with newlines,
    dropping section markers (reference models/base.py:259-273)."""
    parts: List[str] = []
    for item in prompt_template:
        if isinstance(item, dict) and set(item.keys()) == {'section', 'pos'}:
            continue
        if isinstance(item, str):
            if item:
                parts.append(item)
        elif item.get('prompt', ''):
            parts.append(item['prompt'])
    return '\n'.join(parts)


class LMTemplateParser(MetaTemplateWalker):
    """Folds the prompt IR into a single flat string for LM-style models."""

    def parse_template(self, prompt_template: PromptType, mode: str):
        assert mode in ('ppl', 'gen')
        if isinstance(prompt_template, list) \
                and not isinstance(prompt_template, PromptList):
            return [self.parse_template(p, mode) for p in prompt_template]
        if isinstance(prompt_template, str):
            return prompt_template
        if not self.meta_template:
            return _flatten_without_meta(prompt_template)

        prompt = ''
        generate = True
        for kind, payload in self.walk(prompt_template, mode):
            if not generate:
                break
            if kind == 'str':
                prompt += payload
            elif kind == 'round':
                round_spec, role_dict, for_gen = payload
                piece, generate = self._items2str(round_spec, role_dict,
                                                  for_gen)
                prompt += piece
            else:  # single role in begin/end section
                item, role_dict, for_gen = payload
                piece, generate = self._items2str(item, role_dict, for_gen)
                prompt += piece

        prompt = self.meta_template.get('begin', '') + prompt
        if generate:
            prompt += self.meta_template.get('end', '')
        return prompt

    def _items2str(self, spec, role_dict, for_gen) -> Tuple[str, bool]:
        if isinstance(spec, str):
            return spec, True
        if isinstance(spec, dict):
            cfg = role_dict.get(spec['role'],
                                role_dict.get(spec.get('fallback_role')))
            out = cfg.get('begin', '')
            if for_gen and cfg.get('generate', False):
                return out, False
            out += cfg.get('prompt', '') + cfg.get('end', '')
            return out, True
        out = ''
        cont = True
        for item in spec:
            piece, cont = self._items2str(item, role_dict, for_gen)
            out += piece
            if not cont:
                break
        return out, cont


class BaseModel(abc.ABC):
    """Base class for all model wrappers.

    Args:
        path: checkpoint path / model identifier.
        max_seq_len: hard context limit — inferencers' truncation loops use it.
        tokenizer_only: load only the tokenizer (for prompt viewing / length
            measurement without touching the accelerator).
        meta_template: the model's role template (see module docstring).
    """

    is_api: bool = False
    # opt-in for the inferencers' length-aware batch planner
    # (icl/inferencers/schedule.py): True means batches may be reordered
    # and re-packed under a token budget (results are scattered back to
    # original indices, so per-row outputs are unchanged).  API models
    # keep arrival order; JaxLM turns this on.
    supports_batch_plan: bool = False
    # eligibility for the content-addressed result store
    # (opencompass_tpu/store/): True means this model's outputs are pure
    # functions of (prompt, params), so a row evaluated once may be
    # served from disk forever.  API models opt out — sampled
    # completions and provider-side drift break the purity assumption.
    supports_result_cache: bool = True

    def __init__(self,
                 path: str,
                 max_seq_len: int = 2048,
                 tokenizer_only: bool = False,
                 meta_template: Optional[Dict] = None,
                 generation_kwargs: Optional[Dict] = None):
        self.path = path
        self.max_seq_len = max_seq_len
        self.tokenizer_only = tokenizer_only
        self.template_parser = LMTemplateParser(meta_template)
        self.generation_kwargs = generation_kwargs or {}
        self.perf = PerfCounters()
        # flight-recorder call queue (obs/timeline.py): device models
        # push one dict per dispatched device call (_tl_track) with the
        # host-enqueue/fetch wall split; the inferencer's batch recorder
        # pops exactly the calls its dispatch made (FIFO — the pipeline
        # collects batches in dispatch order)
        # bounded: calls dispatched outside a recorded plan (warm-up
        # probes, ad-hoc model use) would otherwise accumulate forever
        self._tl_pending: deque = deque(maxlen=1024)
        self._tl_call_count = 0
        self.eos_token_id = None
        if meta_template and 'eos_token_id' in meta_template:
            self.eos_token_id = meta_template['eos_token_id']

    def _tl_track(self, kind: str, shape, first: bool,
                  prefill_tokens: int) -> Optional[Dict]:
        """Register one device call with the flight recorder (no-op —
        returning None — when no timeline is installed).  The caller
        keeps mutating the returned dict (``fetch_s``,
        ``decode_tokens``) until the host fetch completes; the recorder
        serializes it at batch-collect time."""
        from opencompass_tpu.obs import get_timeline
        if not get_timeline().enabled:
            return None
        info = {'kind': kind, 'shape': [int(shape[0]), int(shape[1])],
                'first': bool(first),
                'prefill_tokens': int(prefill_tokens),
                'dispatch_s': 0.0}
        self._tl_pending.append(info)
        self._tl_call_count += 1
        return info

    def pop_batch_calls(self, n: int):
        """Drain the ``n`` oldest tracked calls (the ones a batch's
        dispatch made) for the flight recorder.  Never raises."""
        out = []
        try:
            for _ in range(int(n)):
                if not self._tl_pending:
                    break
                info = self._tl_pending.popleft()
                out.append({k: (round(v, 6) if isinstance(v, float)
                                else v) for k, v in info.items()})
        except Exception:
            pass
        return out

    @abc.abstractmethod
    def generate(self, inputs: List[str], max_out_len: int) -> List[str]:
        """Greedy/sampled completion for each input string."""

    @abc.abstractmethod
    def get_ppl(self,
                inputs: List[str],
                mask_length: Optional[List[int]] = None) -> List[float]:
        """Mean per-token NLL of each input.  With ``mask_length``, the first
        ``mask_length[i]`` tokens are excluded (normalized-PPL mode)."""

    @abc.abstractmethod
    def get_token_len(self, prompt: str) -> int:
        """Tokenized length of ``prompt``."""

    def choice(self, inputs: List[str], choices: List[str]) -> List[str]:
        """Pick the choice with the highest conditional log prob of its full
        token sequence given the input (reference models/glm.py:132-164
        ``cond_log_prob`` measurement).  Default implementation scores every
        (input, choice) pair through ``get_ppl`` with the input masked out,
        converting mean answer-token NLL back to a summed log prob so
        different-length choices compare fairly."""
        max_ans = max(self.get_token_len(c) for c in choices)
        texts, ctx_lens, ans_lens = [], [], []
        for inp in inputs:
            # scoring batches truncate from the tail, so an over-long
            # context would silently cut off the answer tokens and score
            # every choice 0 — drop the oldest context until it fits
            budget = self.max_seq_len - max_ans - 1
            while inp and self.get_token_len(inp) > budget:
                inp = inp[max(len(inp) // 8, 1):]
            ctx = self.get_token_len(inp)
            for c in choices:
                full = inp + c
                texts.append(full)
                ctx_lens.append(ctx)
                ans_lens.append(max(self.get_token_len(full) - ctx, 1))
        nll = self.get_ppl(texts, mask_length=ctx_lens)
        n = len(choices)
        out = []
        for i in range(len(inputs)):
            scores = [-nll[i * n + j] * ans_lens[i * n + j]
                      for j in range(n)]
            out.append(choices[scores.index(max(scores))])
        return out

    def save_caches(self):
        """Persist any host-side caches worth sharing with successor
        processes (token-length measurements, …).  The infer task calls
        this when a model's datasets finish; base models hold nothing
        persistable."""

    # -- batch planning / async dispatch hooks -----------------------------

    def plan_shape(self, n_rows: int, longest: int,
                   max_len: Optional[int] = None) -> Tuple[int, int]:
        """Padded device shape ``(B, S)`` for a batch of ``n_rows`` rows
        whose longest row is ``longest`` tokens.  The batch planner uses
        it to cost candidate batches; models with bucketed static shapes
        (JaxLM) override it to mirror their padder exactly."""
        longest = max(int(longest), 1)
        if max_len is not None:
            longest = min(longest, max(int(max_len), 1))
        return max(int(n_rows), 1), longest

    def generate_async(self, inputs: List[str], max_out_len: int):
        """Dispatch one generation batch; returns a handle whose
        ``result()`` yields what :meth:`generate` would.  Default is
        synchronous — accelerator models override to enqueue the device
        work and defer the host fetch, enabling the inferencers' double-
        buffered pipeline."""
        return _Ready(self.generate(inputs, max_out_len=max_out_len))

    def get_ppl_async(self, inputs: List[str],
                      mask_length: Optional[List[int]] = None):
        """Async counterpart of :meth:`get_ppl` (see generate_async)."""
        return _Ready(self.get_ppl(inputs, mask_length))

    def get_choice_logprobs_async(self, inputs: List[str],
                                  choices: List[str]):
        """Async counterpart of ``get_choice_logprobs`` for models that
        implement it (raises AttributeError otherwise, same as the sync
        call would)."""
        return _Ready(self.get_choice_logprobs(inputs, choices))

    # -- template-aware entry points used by inferencers -------------------
    def parse_template(self, prompt_template: PromptType, mode: str):
        return self.template_parser.parse_template(prompt_template, mode)

    def get_ppl_from_template(self, templates, mask_length=None):
        inputs = self.parse_template(templates, mode='ppl')
        return self.get_ppl(inputs, mask_length)

    def get_ppl_from_template_async(self, templates, mask_length=None):
        # models without a real dispatch/fetch split go through the SYNC
        # template method so subclass overrides of it keep observing
        # every batch; models with real async primitives skip it
        if type(self).get_ppl_async is BaseModel.get_ppl_async:
            return _Ready(self.get_ppl_from_template(
                templates, mask_length=mask_length))
        inputs = self.parse_template(templates, mode='ppl')
        return self.get_ppl_async(inputs, mask_length)

    def generate_from_template(self, templates, max_out_len: int):
        inputs = self.parse_template(templates, mode='gen')
        return self.generate(inputs, max_out_len=max_out_len)

    def generate_from_template_async(self, templates, max_out_len: int):
        if type(self).generate_async is BaseModel.generate_async:
            return _Ready(self.generate_from_template(
                templates, max_out_len=max_out_len))
        inputs = self.parse_template(templates, mode='gen')
        return self.generate_async(inputs, max_out_len=max_out_len)

    def get_token_len_from_template(self, templates, mode: str = 'ppl'):
        prompts = self.parse_template(templates, mode=mode)
        is_batched = isinstance(prompts, list) \
            and not isinstance(prompts, PromptList)
        if not is_batched:
            prompts = [prompts]
        lens = [self.get_token_len(str(p)) for p in prompts]
        return lens if is_batched else lens[0]
