"""API-model abstraction: chat-message template parsing + rate limiting.

API models receive the prompt IR as a list of ``{'role': api_role, 'prompt':
text}`` chat messages rather than a flat string.  Consecutive same-role
messages are merged; gen-mode parsing stops before the first role marked
``generate: True`` (the assistant turn the API will produce).

Behavioral parity: reference opencompass/models/base_api.py:17-399
(BaseAPIModel, APITemplateParser, TokenBucket).
"""
from __future__ import annotations

import re
import threading
import warnings
from abc import abstractmethod
from time import sleep
from typing import Dict, List, Optional, Tuple, Union

from opencompass_tpu.utils.prompt import PromptList

from .base import BaseModel, MetaTemplateWalker

PromptType = Union[PromptList, str]


class TokenBucket:
    """Semaphore refilled by a daemon thread at ``rate`` tokens/sec, used to
    cap API queries-per-second across the inferencer's worker threads."""

    def __init__(self, rate: float):
        self._rate = rate
        self._tokens = threading.Semaphore(0)
        self._started = False

    def _refill(self):
        while True:
            if self._tokens._value < self._rate:
                self._tokens.release()
            sleep(1 / self._rate)

    def get_token(self):
        if not self._started:
            self._started = True
            threading.Thread(target=self._refill, daemon=True).start()
        self._tokens.acquire()


class APITemplateParser(MetaTemplateWalker):
    """Folds the prompt IR into a chat-message PromptList for API models."""

    def parse_template(self, prompt_template: PromptType, mode: str):
        assert mode in ('ppl', 'gen')
        if isinstance(prompt_template, list) \
                and not isinstance(prompt_template, PromptList):
            return [self.parse_template(p, mode) for p in prompt_template]
        if isinstance(prompt_template, str):
            return prompt_template
        if not self.meta_template:
            # Flatten to newline-joined plain text.
            parts = []
            for item in prompt_template:
                if isinstance(item, dict) \
                        and set(item.keys()) == {'section', 'pos'}:
                    continue
                if isinstance(item, str):
                    if item:
                        parts.append(item)
                elif item.get('prompt', ''):
                    parts.append(item['prompt'])
            return '\n'.join(parts)

        messages = PromptList()
        generate = True
        for kind, payload in self.walk(prompt_template, mode):
            if not generate:
                break
            if kind == 'str':
                if payload.strip():
                    warnings.warn('Non-empty raw string in prompt template '
                                  'is dropped for API models.')
            elif kind == 'round':
                round_spec, role_dict, for_gen = payload
                out, generate = self._items2api(round_spec, role_dict, for_gen)
                messages += out
            else:
                item, role_dict, for_gen = payload
                out, generate = self._items2api(item, role_dict, for_gen)
                if isinstance(out, dict):
                    messages.append(out)
                else:
                    messages += out

        # Merge consecutive same-role messages.
        if messages:
            merged = PromptList([messages[0]])
            for item in messages[1:]:
                if item['role'] == merged[-1]['role']:
                    merged[-1]['prompt'] += '\n' + item['prompt']
                else:
                    merged.append(item)
            messages = merged
        return messages

    def _items2api(self, spec, role_dict, for_gen) -> Tuple[list, bool]:
        if isinstance(spec, dict):
            msg, cont = self._role2message(spec, role_dict, for_gen)
            return msg, cont
        out = []
        cont = True
        for item in spec:
            if isinstance(item, str):
                raise TypeError('Raw strings without an explicit role are not '
                                'allowed in API meta templates.')
            msg, cont = self._role2message(item, role_dict, for_gen)
            if msg:
                out.append(msg)
            if not cont:
                break
        return out, cont

    def _role2message(self, role_prompt, role_dict,
                      for_gen) -> Tuple[Optional[dict], bool]:
        cfg = role_dict.get(role_prompt['role'],
                            role_dict.get(role_prompt.get('fallback_role')))
        if for_gen and cfg.get('generate', False):
            return None, False
        prompt = cfg.get('begin', '') + cfg.get('prompt', '') \
            + cfg.get('end', '')
        return {'role': cfg['api_role'], 'prompt': prompt}, True


class BaseAPIModel(BaseModel):
    """Base class for API-served models.

    Args:
        path: model identifier passed to the API.
        query_per_second: rate limit enforced via :class:`TokenBucket`.
        retry: attempts per query before giving up.
    """

    is_api: bool = True
    # API completions are not pure functions of the prompt (sampling,
    # provider-side model drift) — never serve them from the result store
    supports_result_cache: bool = False

    def __init__(self,
                 path: str,
                 query_per_second: int = 1,
                 retry: int = 2,
                 max_seq_len: int = 2048,
                 meta_template: Optional[Dict] = None,
                 generation_kwargs: Optional[Dict] = None):
        self.path = path
        self.max_seq_len = max_seq_len
        self.meta_template = meta_template
        self.retry = retry
        self.query_per_second = query_per_second
        self.token_bucket = TokenBucket(query_per_second)
        self.template_parser = APITemplateParser(meta_template)
        self.generation_kwargs = generation_kwargs or {}
        self.logger = None

    @abstractmethod
    def generate(self, inputs: List[PromptType], max_out_len: int) -> List[str]:
        """Generate completions via the API."""

    def get_ppl(self, inputs, mask_length=None):
        raise NotImplementedError(
            f'{type(self).__name__} does not support PPL-mode evaluation.')

    def get_token_len(self, prompt: str) -> int:
        """Heuristic token count without a real tokenizer: English words +
        CJK characters (reference base_api.py:82-103)."""
        english_parts = re.sub(r'[一-鿿]+', ' ', prompt)
        english_count = sum(1 for part in english_parts.split() if part)
        chinese_count = sum(1 for ch in prompt if '一' <= ch <= '鿿')
        return english_count + chinese_count

    def wait(self):
        """Block until the rate limiter grants the next query."""
        return self.token_bucket.get_token()

    def post_json(self, url: str, body: Dict,
                  headers: Optional[Dict] = None,
                  timeout: float = 120) -> Dict:
        """Rate-limited JSON POST with the shared retry policy: 429 backs
        off exponentially, other 4xx fail fast (retrying cannot fix auth or
        a bad request), 5xx/network errors burn the retry budget; the
        final error chains the last underlying exception."""
        import json as _json
        import urllib.error
        import urllib.request
        from opencompass_tpu.utils.logging import get_logger
        logger = get_logger()
        hdrs = {'Content-Type': 'application/json', **(headers or {})}
        last_exc = None
        for attempt in range(self.retry + 1):
            self.wait()
            try:
                request = urllib.request.Request(
                    url, data=_json.dumps(body).encode(), headers=hdrs)
                with urllib.request.urlopen(request,
                                            timeout=timeout) as resp:
                    return _json.loads(resp.read())
            except urllib.error.HTTPError as err:
                if err.code == 429:
                    logger.warning('rate limited; backing off')
                elif 400 <= err.code < 500:
                    raise RuntimeError(
                        f'API rejected the request ({err.code} '
                        f'{err.reason}, {url})') from err
                else:
                    logger.error(f'API error {err.code}: {err.reason}')
                last_exc = err
                if attempt < self.retry:  # no pointless terminal sleep
                    sleep(2 ** attempt)   # 429/5xx: back off, don't hammer
            except Exception as exc:  # noqa: BLE001 — network variance
                logger.error(f'API request failed: {exc}')
                last_exc = exc
                if attempt < self.retry:
                    sleep(1)
        raise RuntimeError(
            f'API request failed after {self.retry + 1} attempts '
            f'({url})') from last_exc
