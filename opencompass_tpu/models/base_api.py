"""API-model abstraction: chat-message template parsing + rate limiting.

API models receive the prompt IR as a list of ``{'role': api_role, 'prompt':
text}`` chat messages rather than a flat string.  Consecutive same-role
messages are merged; gen-mode parsing stops before the first role marked
``generate: True`` (the assistant turn the API will produce).

Behavioral parity: reference opencompass/models/base_api.py:17-399
(BaseAPIModel, APITemplateParser, TokenBucket).
"""
from __future__ import annotations

import re
import threading
import time
import warnings
from time import sleep
from typing import Callable, Dict, List, Optional, Tuple, Union

from opencompass_tpu.utils.prompt import PromptList

from .base import BaseModel, MetaTemplateWalker

PromptType = Union[PromptList, str]


def _request_deadline_remaining_s() -> Optional[float]:
    """Remaining wall budget for the running request, when one is
    active in this thread: the outbound scheduler's row deadline (it
    re-publishes the budget on its worker threads), else the serve
    path's ``X-OCT-Deadline-Ms`` request context — both lookups live
    in ``outbound/scheduler.py``; this is just the precedence."""
    try:
        from opencompass_tpu.outbound.scheduler import (
            current_row_deadline_s, serve_deadline_remaining_s)
        remaining = current_row_deadline_s()
        if remaining is not None:
            return remaining
        return serve_deadline_remaining_s()
    except Exception:  # noqa: BLE001 — never block transport on obs
        return None


class TokenBucket:
    """QPS cap as a lazily-refilled token counter (parity shim).

    The original shape — a ``Semaphore`` refilled by a per-model
    daemon thread — had three races the outbound scheduler's limiter
    superseded: unsynchronized ``_started`` could spawn two refill
    threads (double the configured rate), ``_refill`` poked the
    private ``Semaphore._value``, and the busy thread never died with
    the model.  This shim keeps the ``get_token()`` contract for
    legacy callers but accrues tokens arithmetically under a lock on
    an injected clock — no thread, no private attrs, nothing to leak.
    New code paces through :class:`opencompass_tpu.outbound.Pacer`.
    """

    def __init__(self, rate: float):
        self._rate = max(float(rate), 1e-6)
        # burst matches the old semaphore's cap (value < rate)
        self._burst = max(self._rate, 1.0)
        self._lock = threading.Lock()
        # guarded-by: _lock
        self._tokens = 1.0
        # guarded-by: _lock
        self._last: Optional[float] = None

    def try_take(self, now: Optional[float] = None) -> float:
        """Take one token if available (returns 0.0), else the seconds
        until one accrues — deterministic under an injected clock."""
        now = time.monotonic() if now is None else float(now)
        with self._lock:
            if self._last is None:
                self._last = now
            self._tokens = min(
                self._burst,
                self._tokens + (now - self._last) * self._rate)
            self._last = now
            if self._tokens >= 1.0:
                self._tokens -= 1.0
                return 0.0
            return (1.0 - self._tokens) / self._rate

    def get_token(self):
        """Block until the next token (the legacy pacing call)."""
        while True:
            wait = self.try_take()
            if wait <= 0.0:
                return
            sleep(min(wait, 1.0))


class APITemplateParser(MetaTemplateWalker):
    """Folds the prompt IR into a chat-message PromptList for API models."""

    def parse_template(self, prompt_template: PromptType, mode: str):
        assert mode in ('ppl', 'gen')
        if isinstance(prompt_template, list) \
                and not isinstance(prompt_template, PromptList):
            return [self.parse_template(p, mode) for p in prompt_template]
        if isinstance(prompt_template, str):
            return prompt_template
        if not self.meta_template:
            # Flatten to newline-joined plain text.
            parts = []
            for item in prompt_template:
                if isinstance(item, dict) \
                        and set(item.keys()) == {'section', 'pos'}:
                    continue
                if isinstance(item, str):
                    if item:
                        parts.append(item)
                elif item.get('prompt', ''):
                    parts.append(item['prompt'])
            return '\n'.join(parts)

        messages = PromptList()
        generate = True
        for kind, payload in self.walk(prompt_template, mode):
            if not generate:
                break
            if kind == 'str':
                if payload.strip():
                    warnings.warn('Non-empty raw string in prompt template '
                                  'is dropped for API models.')
            elif kind == 'round':
                round_spec, role_dict, for_gen = payload
                out, generate = self._items2api(round_spec, role_dict, for_gen)
                messages += out
            else:
                item, role_dict, for_gen = payload
                out, generate = self._items2api(item, role_dict, for_gen)
                if isinstance(out, dict):
                    messages.append(out)
                else:
                    messages += out

        # Merge consecutive same-role messages.
        if messages:
            merged = PromptList([messages[0]])
            for item in messages[1:]:
                if item['role'] == merged[-1]['role']:
                    merged[-1]['prompt'] += '\n' + item['prompt']
                else:
                    merged.append(item)
            messages = merged
        return messages

    def _items2api(self, spec, role_dict, for_gen) -> Tuple[list, bool]:
        if isinstance(spec, dict):
            msg, cont = self._role2message(spec, role_dict, for_gen)
            return msg, cont
        out = []
        cont = True
        for item in spec:
            if isinstance(item, str):
                raise TypeError('Raw strings without an explicit role are not '
                                'allowed in API meta templates.')
            msg, cont = self._role2message(item, role_dict, for_gen)
            if msg:
                out.append(msg)
            if not cont:
                break
        return out, cont

    def _role2message(self, role_prompt, role_dict,
                      for_gen) -> Tuple[Optional[dict], bool]:
        cfg = role_dict.get(role_prompt['role'],
                            role_dict.get(role_prompt.get('fallback_role')))
        if for_gen and cfg.get('generate', False):
            return None, False
        prompt = cfg.get('begin', '') + cfg.get('prompt', '') \
            + cfg.get('end', '')
        return {'role': cfg['api_role'], 'prompt': prompt}, True


class BaseAPIModel(BaseModel):
    """Base class for API-served models.

    Args:
        path: model identifier passed to the API.
        query_per_second: steady pacing cap, honored by the outbound
            scheduler's :class:`~opencompass_tpu.outbound.Pacer` (and
            the legacy :class:`TokenBucket` shim for direct
            ``post_json`` callers).
        retry: attempts per query before giving up (the scheduler's
            ``max_attempts`` is ``retry + 1``, budget permitting).
        max_inflight: AIMD ceiling on concurrent in-flight requests
            per provider — the adaptive window backs off from here on
            429/5xx and re-probes on success.
        hedge_after_s: when set, a request still in flight after this
            many seconds launches one budgeted duplicate (first
            completion wins) — the straggler-tail lever.
        outbound: advanced scheduler overrides
            (docs/user_guides/api_models.md): ``qps``,
            ``request_timeout_s``, ``breaker_failures`` /
            ``breaker_window_s`` / ``breaker_cooldown_s``,
            ``retry_budget_rate`` / ``retry_budget_burst``.
    """

    is_api: bool = True
    # API completions are not pure functions of the prompt (sampling,
    # provider-side model drift) — never serve them from the result store
    supports_result_cache: bool = False

    def __init__(self,
                 path: str,
                 query_per_second: int = 1,
                 retry: int = 2,
                 max_seq_len: int = 2048,
                 meta_template: Optional[Dict] = None,
                 generation_kwargs: Optional[Dict] = None,
                 max_inflight: int = 8,
                 hedge_after_s: Optional[float] = None,
                 outbound: Optional[Dict] = None):
        self.path = path
        self.max_seq_len = max_seq_len
        self.meta_template = meta_template
        self.retry = retry
        self.query_per_second = query_per_second
        self.token_bucket = TokenBucket(query_per_second)
        self.template_parser = APITemplateParser(meta_template)
        self.generation_kwargs = generation_kwargs or {}
        self.max_inflight = max_inflight
        self.hedge_after_s = hedge_after_s
        self.outbound_cfg = dict(outbound or {})
        self.logger = None
        self._outbound_lock = threading.Lock()
        # guarded-by: _outbound_lock
        self._outbound_sched = None

    # -- outbound scheduling -----------------------------------------------

    @property
    def provider_key(self) -> str:
        """The provider identity outbound state (breaker, AIMD window,
        retry budget, metrics labels) is keyed by: the endpoint host
        when the model has a URL, else the model path."""
        url = getattr(self, 'url', '') or ''
        try:
            from urllib.parse import urlsplit
            netloc = urlsplit(url).netloc
        except ValueError:
            netloc = ''
        return netloc or self.path

    @property
    def supports_outbound(self) -> bool:
        """True when this model routes rows through the outbound
        scheduler (it implements the single-attempt ``_generate_one``
        hook) — the gate for the inferencer's per-row scatter-back
        path."""
        return type(self)._generate_one \
            is not BaseAPIModel._generate_one

    def outbound_scheduler(self):
        """The model's lazily-built per-provider scheduler — every
        generate/ppl/choice row flows through it."""
        with self._outbound_lock:
            if self._outbound_sched is None:
                from opencompass_tpu.outbound import OutboundScheduler
                from opencompass_tpu.utils.resilience import (
                    CircuitBreaker, RetryBudget)
                from opencompass_tpu.outbound.scheduler import (
                    OUTBOUND_RETRY_BURST, OUTBOUND_RETRY_RATE)
                cfg = self.outbound_cfg
                key = self.provider_key
                breaker = CircuitBreaker(
                    key,
                    failures=cfg.get('breaker_failures', 3),
                    window_s=cfg.get('breaker_window_s', 60.0),
                    cooldown_s=cfg.get('breaker_cooldown_s', 15.0))
                budget = RetryBudget(
                    rate=cfg.get('retry_budget_rate',
                                 OUTBOUND_RETRY_RATE),
                    burst=cfg.get('retry_budget_burst',
                                  OUTBOUND_RETRY_BURST))
                self._outbound_sched = OutboundScheduler(
                    key,
                    max_inflight=cfg.get('max_inflight',
                                         self.max_inflight),
                    qps=cfg.get('qps', self.query_per_second),
                    max_attempts=self.retry + 1,
                    request_timeout_s=cfg.get('request_timeout_s',
                                              60.0),
                    hedge_after_s=cfg.get('hedge_after_s',
                                          self.hedge_after_s),
                    retry_budget=budget, breaker=breaker)
            return self._outbound_sched

    def _generate_one(self, prompt: PromptType, max_out_len: int,
                      timeout: float = 60.0) -> str:
        """ONE un-retried completion attempt for one prompt, raising
        typed :mod:`opencompass_tpu.outbound.errors`.  Subclasses
        implement this; the scheduler owns retries/pacing/breakers."""
        raise NotImplementedError(
            f'{type(self).__name__} does not implement the outbound '
            'single-attempt hook')

    def generate_outcomes(self, inputs: List[PromptType],
                          max_out_len: int,
                          on_result: Optional[Callable] = None,
                          deadline_s: Optional[float] = None,
                          fail_fast: bool = True):
        """Drive ``inputs`` through the outbound scheduler to typed
        per-row outcomes (:class:`opencompass_tpu.outbound
        .OutboundReport`).  ``on_result(index, text)`` fires per
        completed row in completion order — the scatter-back hook the
        inferencer's partial-failure path rides."""

        def call(prompt, timeout):
            return self._generate_one(prompt, max_out_len,
                                      timeout=timeout)

        return self.outbound_scheduler().run(
            list(inputs), call, on_result=on_result,
            deadline_s=deadline_s, fail_fast=fail_fast)

    def generate(self, inputs: List[PromptType],
                 max_out_len: int = 512) -> List[str]:
        """Generate completions via the API, concurrently through the
        outbound scheduler.  Any row failing past its budgets raises
        :class:`~opencompass_tpu.outbound.PartialFailure` (the task
        fails resumable rather than scoring '' as a wrong answer);
        a non-retryable rejection fail-fasts the remaining queue."""
        return self.generate_outcomes(inputs, max_out_len).values()

    def get_ppl(self, inputs, mask_length=None):
        raise NotImplementedError(
            f'{type(self).__name__} does not support PPL-mode evaluation.')

    def get_token_len(self, prompt: str) -> int:
        """Heuristic token count without a real tokenizer: English words +
        CJK characters (reference base_api.py:82-103)."""
        english_parts = re.sub(r'[一-鿿]+', ' ', prompt)
        english_count = sum(1 for part in english_parts.split() if part)
        chinese_count = sum(1 for ch in prompt if '一' <= ch <= '鿿')
        return english_count + chinese_count

    def wait(self):
        """Block until the rate limiter grants the next query."""
        return self.token_bucket.get_token()

    def post_json_once(self, url: str, body: Dict,
                       headers: Optional[Dict] = None,
                       timeout: float = 120) -> Dict:
        """ONE JSON POST attempt with typed failures
        (:mod:`opencompass_tpu.outbound.errors`) — the transport the
        outbound scheduler drives.  When a serve-path request deadline
        is active (``X-OCT-Deadline-Ms``), the remaining budget is
        forwarded on the outbound request and caps the socket
        timeout."""
        import json as _json
        import urllib.request
        from opencompass_tpu.outbound import errors as oerr
        hdrs = {'Content-Type': 'application/json', **(headers or {})}
        remaining = _request_deadline_remaining_s()
        if remaining is not None:
            if remaining <= 0:
                raise oerr.DeadlineExceeded(
                    'request budget exhausted before dispatch')
            hdrs.setdefault('X-OCT-Deadline-Ms',
                            str(int(remaining * 1000)))
            timeout = min(timeout, max(remaining, 0.05))
        try:
            data = _json.dumps(body).encode()
        except (TypeError, ValueError) as exc:
            # a client-side bug, not a provider fault: retrying the
            # same un-serializable body (or opening the breaker over
            # it) would misattribute the incident — fail fast, typed
            raise oerr.Rejected(
                f'request body is not JSON-serializable: '
                f'{exc}') from exc
        request = urllib.request.Request(url, data=data, headers=hdrs)
        try:
            with urllib.request.urlopen(request,
                                        timeout=timeout) as resp:
                raw = resp.read()
        except oerr.ProviderError:
            raise
        except Exception as exc:  # noqa: BLE001 — classified below
            raise oerr.classify(exc) from exc
        try:
            return _json.loads(raw)
        except ValueError as exc:
            raise oerr.MalformedResponse(
                f'unparseable JSON from {url}: {exc}') from exc

    def post_json(self, url: str, body: Dict,
                  headers: Optional[Dict] = None,
                  timeout: float = 120) -> Dict:
        """Rate-limited JSON POST with the shared retry policy: 429
        honors the provider's ``Retry-After`` header, every backoff is
        exponential with *deterministic jitter* (the serve daemon's
        ``backoff_delay`` — concurrent callers decorrelate instead of
        stampeding an already-throttling provider in lockstep), other
        4xx fail fast (retrying cannot fix auth or a bad request),
        5xx/network errors burn the retry budget; the final error
        chains the last underlying exception.

        Direct callers only — rows going through the scheduler use
        :meth:`post_json_once` and the scheduler's own policy."""
        from opencompass_tpu.outbound import errors as oerr
        from opencompass_tpu.utils.logging import get_logger
        from opencompass_tpu.utils.resilience import backoff_delay
        logger = get_logger()
        last_exc = None
        for attempt in range(self.retry + 1):
            self.wait()
            try:
                return self.post_json_once(url, body, headers=headers,
                                           timeout=timeout)
            except oerr.Rejected as err:
                raise RuntimeError(
                    f'API rejected the request ({err}, '
                    f'{url})') from err
            except oerr.ProviderError as err:
                last_exc = err
                if not err.retryable:
                    # e.g. an expired request deadline: another
                    # attempt cannot succeed — fail now, no backoff
                    raise RuntimeError(
                        f'API request failed ({err}, {url})') from err
                if isinstance(err, oerr.RateLimited):
                    logger.warning(
                        'rate limited; backing off'
                        + (f' (Retry-After {err.retry_after_s}s)'
                           if err.retry_after_s is not None else ''))
                else:
                    logger.error(f'API error: {err}')
                if attempt < self.retry:  # no pointless terminal sleep
                    delay = backoff_delay(url, attempt, base_s=1.0,
                                          cap_s=30.0)
                    if err.retry_after_s is not None:
                        # the provider named its recovery horizon;
                        # coming back earlier only earns another 429
                        delay = max(delay, err.retry_after_s)
                    sleep(delay)
        raise RuntimeError(
            f'API request failed after {self.retry + 1} attempts '
            f'({url})') from last_exc
