from .base import BaseModel, LMTemplateParser  # noqa
from .base_api import APITemplateParser, BaseAPIModel, TokenBucket  # noqa
from .fake import FakeModel  # noqa

__all__ = [
    'BaseModel', 'LMTemplateParser', 'APITemplateParser', 'BaseAPIModel',
    'TokenBucket', 'FakeModel'
]
