from .base import BaseModel, LMTemplateParser  # noqa
from .base_api import APITemplateParser, BaseAPIModel, TokenBucket  # noqa
from .completions_api import CompletionsAPI  # noqa
from .fake import FakeModel  # noqa
from .glm import GLM130B  # noqa
from .jax_lm import JaxLM  # noqa
from .openai_api import OpenAI  # noqa
from .tokenizer import ByteTokenizer, load_tokenizer  # noqa

__all__ = [
    'BaseModel', 'LMTemplateParser', 'APITemplateParser', 'BaseAPIModel',
    'CompletionsAPI', 'TokenBucket', 'FakeModel', 'GLM130B', 'JaxLM',
    'OpenAI', 'ByteTokenizer', 'load_tokenizer'
]
