from .base import BaseModel, LMTemplateParser  # noqa
from .base_api import APITemplateParser, BaseAPIModel, TokenBucket  # noqa
from .fake import FakeModel  # noqa
from .jax_lm import JaxLM  # noqa
from .tokenizer import ByteTokenizer, load_tokenizer  # noqa

__all__ = [
    'BaseModel', 'LMTemplateParser', 'APITemplateParser', 'BaseAPIModel',
    'TokenBucket', 'FakeModel', 'JaxLM', 'ByteTokenizer', 'load_tokenizer'
]
