"""Deterministic fake model for hermetic pipeline tests.

The reference ships no model fakes (its tests never touch a model — reference
tests/ are template-only); this fills that gap per SURVEY.md §4 so the full
infer → eval → summarize pipeline runs on CPU with reproducible outputs.
"""
import hashlib
import zlib
from typing import Dict, List, Optional

from opencompass_tpu.registry import MODELS

from .base import BaseModel


@MODELS.register_module()
class FakeModel(BaseModel):
    """A model whose outputs are pure functions of its inputs.

    * ``generate``: echoes a deterministic digest of the prompt, or, when
      ``canned_responses`` maps a substring of the prompt to an answer,
      returns that answer (lets tests construct known accuracy outcomes).
    * ``get_ppl``: stable per-string pseudo-perplexity via crc32, or the value
      from ``canned_ppls`` for prompts containing a given substring.
    * ``get_token_len``: whitespace token count (×1 token per word).
    """

    # mirrors JaxLM's continuous-batching contract so the inferencer's
    # feed-queue wiring (out-of-order retirement, per-row flush/commit)
    # is testable without a device
    supports_continuous_batching = True

    def __init__(self,
                 path: str = 'fake',
                 max_seq_len: int = 2048,
                 meta_template: Optional[Dict] = None,
                 canned_responses: Optional[Dict[str, str]] = None,
                 canned_ppls: Optional[Dict[str, float]] = None,
                 continuous: bool = False,
                 tokenizer_only: bool = False):
        super().__init__(path=path,
                         max_seq_len=max_seq_len,
                         tokenizer_only=tokenizer_only,
                         meta_template=meta_template)
        self.canned_responses = canned_responses or {}
        self.canned_ppls = canned_ppls or {}
        self.continuous_batching = continuous

    @property
    def continuous_active(self) -> bool:
        return self.continuous_batching

    def generate_continuous(self, inputs: List[str], max_out_len: int,
                            on_result=None, stats_out=None) -> List[str]:
        """FakeModel 'engine': same pure outputs as :meth:`generate`,
        delivered per row in the engine's feed order (longest prompt
        first) — deliberately NOT dataset order, so callers must
        scatter results back exactly as they would for the real
        engine's out-of-order retirements."""
        texts = self.generate(list(inputs), max_out_len=max_out_len)
        order = sorted(range(len(texts)),
                       key=lambda i: (-len(str(inputs[i]).split()), i))
        for k in order:
            if on_result is not None:
                on_result(k, texts[k])
        if stats_out is not None:
            stats_out['prefill_tokens'] = sum(
                self.get_token_len(str(p)) for p in inputs)
            stats_out['decode_tokens'] = sum(
                self.get_token_len(t) for t in texts)
        return texts

    def generate(self, inputs: List[str], max_out_len: int) -> List[str]:
        self.perf.samples += len(inputs)
        self.perf.calls += 1
        out = []
        for prompt in inputs:
            prompt = str(prompt)
            for key, resp in self.canned_responses.items():
                if key in prompt:
                    out.append(resp)
                    break
            else:
                digest = hashlib.sha256(prompt.encode()).hexdigest()[:8]
                out.append(f'fake-{digest}')
        self.perf.tokens_out += sum(len(o.split()) for o in out)
        return out

    def get_ppl(self,
                inputs: List[str],
                mask_length: Optional[List[int]] = None) -> List[float]:
        self.perf.samples += len(inputs)
        self.perf.calls += 1
        self.perf.tokens_in += sum(
            self.get_token_len(str(p)) for p in inputs)
        out = []
        for prompt in inputs:
            prompt = str(prompt)
            for key, ppl in self.canned_ppls.items():
                if key in prompt:
                    out.append(float(ppl))
                    break
            else:
                out.append(1.0 + (zlib.crc32(prompt.encode()) % 10000) / 100.0)
        return out

    def get_token_len(self, prompt: str) -> int:
        return max(1, len(str(prompt).split()))

    def get_choice_logprobs(self, inputs, choices):
        """Deterministic prob vectors: canned_ppls keys act as (prompt
        substring → preferred choice index via lowest pseudo-PPL)."""
        out = []
        for prompt in inputs:
            scores = [
                1.0 / self.get_ppl([f'{prompt} {choice}'])[0]
                for choice in choices
            ]
            total = sum(scores)
            out.append([s / total for s in scores])
        return out
