"""Deterministic fake model for hermetic pipeline tests.

The reference ships no model fakes (its tests never touch a model — reference
tests/ are template-only); this fills that gap per SURVEY.md §4 so the full
infer → eval → summarize pipeline runs on CPU with reproducible outputs.
"""
import hashlib
import zlib
from typing import Dict, List, Optional

from opencompass_tpu.registry import MODELS

from .base import BaseModel


@MODELS.register_module()
class FakeModel(BaseModel):
    """A model whose outputs are pure functions of its inputs.

    * ``generate``: echoes a deterministic digest of the prompt, or, when
      ``canned_responses`` maps a substring of the prompt to an answer,
      returns that answer (lets tests construct known accuracy outcomes).
    * ``get_ppl``: stable per-string pseudo-perplexity via crc32, or the value
      from ``canned_ppls`` for prompts containing a given substring.
    * ``get_token_len``: whitespace token count (×1 token per word).
    """

    # mirrors JaxLM's continuous-batching contract so the inferencer's
    # feed-queue wiring (out-of-order retirement, per-row flush/commit)
    # is testable without a device
    supports_continuous_batching = True

    def __init__(self,
                 path: str = 'fake',
                 max_seq_len: int = 2048,
                 meta_template: Optional[Dict] = None,
                 canned_responses: Optional[Dict[str, str]] = None,
                 canned_ppls: Optional[Dict[str, float]] = None,
                 continuous: bool = False,
                 tokenizer_only: bool = False):
        super().__init__(path=path,
                         max_seq_len=max_seq_len,
                         tokenizer_only=tokenizer_only,
                         meta_template=meta_template)
        self.canned_responses = canned_responses or {}
        self.canned_ppls = canned_ppls or {}
        self.continuous_batching = continuous

    @property
    def continuous_active(self) -> bool:
        return self.continuous_batching

    def generate_continuous(self, inputs: List[str], max_out_len: int,
                            on_result=None, stats_out=None,
                            interactive: bool = False,
                            on_token=None,
                            cancel_out=None) -> List[str]:
        """FakeModel 'engine': same pure outputs as :meth:`generate`,
        delivered per row in the engine's feed order (longest prompt
        first) — deliberately NOT dataset order, so callers must
        scatter results back exactly as they would for the real
        engine's out-of-order retirements.

        Token emission is *paced*: each output token is stamped with a
        wall-clock timestamp (optionally slowed by
        ``OCT_FAKE_TOKEN_SLEEP_S`` seconds per token), so ``stats_out``
        carries a measured TTFT and inter-token-latency samples through
        exactly the serve plumbing the real engine feeds — the
        device-free ``bench.py --slo`` leg and the reqtrace tests ride
        this.  ``on_token(i, piece, n_emitted)`` mirrors the real
        engine's streaming hook — one whitespace-delimited piece per
        paced token, concatenating exactly to the row's final text —
        and ``cancel_out`` receives a zero-arg cancel callable that
        stops emission mid-row (the cancelled row delivers the partial
        text it streamed so far)."""
        import os
        import re
        import time
        try:
            sleep_s = float(os.environ.get('OCT_FAKE_TOKEN_SLEEP_S')
                            or 0.0)
        except (TypeError, ValueError):
            sleep_s = 0.0
        cancelled: List[bool] = []
        if cancel_out is not None:
            cancel_out.append(lambda: cancelled.append(True))
        t0 = time.perf_counter()
        texts = self.generate(list(inputs), max_out_len=max_out_len)
        order = sorted(range(len(texts)),
                       key=lambda i: (-len(str(inputs[i]).split()), i))
        first_ts = None
        n_cancelled = 0
        itl: List[float] = []
        for k in order:
            # piece boundaries at whitespace->non-space transitions, so
            # ''.join(pieces) == text exactly (streamed concat is
            # token-identical to the buffered reply by construction)
            pieces = re.split(r'(?<=\s)(?=\S)', texts[k]) \
                if texts[k] else ['']
            prev = None
            emitted = 0
            for piece in pieces:
                if cancelled:
                    break
                if sleep_s > 0:
                    time.sleep(min(sleep_s, 1.0))
                now = time.perf_counter()
                if first_ts is None:
                    first_ts = now
                if prev is not None:
                    itl.append(now - prev)
                prev = now
                emitted += 1
                if on_token is not None and piece:
                    on_token(k, piece, emitted)
            if cancelled and emitted < len(pieces):
                n_cancelled += 1
                texts[k] = ''.join(pieces[:emitted])
            if on_result is not None:
                on_result(k, texts[k])
        if stats_out is not None:
            if n_cancelled:
                stats_out['cancelled_rows'] = n_cancelled
            stats_out['prefill_tokens'] = sum(
                self.get_token_len(str(p)) for p in inputs)
            stats_out['decode_tokens'] = sum(
                self.get_token_len(t) for t in texts)
            if first_ts is not None:
                stats_out['ttft_s'] = round(first_ts - t0, 6)
            if itl:
                # the one nearest-rank percentile every surface uses
                from opencompass_tpu.obs.reqtrace import percentile
                stats_out['itl_p50_ms'] = round(
                    percentile(itl, 0.50) * 1e3, 3)
                stats_out['itl_p99_ms'] = round(
                    percentile(itl, 0.99) * 1e3, 3)
                stats_out['itl_ms'] = [round(v * 1e3, 3)
                                       for v in itl[:64]]
        return texts

    def generate(self, inputs: List[str], max_out_len: int) -> List[str]:
        self.perf.samples += len(inputs)
        self.perf.calls += 1
        out = []
        for prompt in inputs:
            prompt = str(prompt)
            for key, resp in self.canned_responses.items():
                if key in prompt:
                    out.append(resp)
                    break
            else:
                digest = hashlib.sha256(prompt.encode()).hexdigest()[:8]
                out.append(f'fake-{digest}')
        self.perf.tokens_out += sum(len(o.split()) for o in out)
        return out

    def get_ppl(self,
                inputs: List[str],
                mask_length: Optional[List[int]] = None) -> List[float]:
        self.perf.samples += len(inputs)
        self.perf.calls += 1
        self.perf.tokens_in += sum(
            self.get_token_len(str(p)) for p in inputs)
        out = []
        for prompt in inputs:
            prompt = str(prompt)
            for key, ppl in self.canned_ppls.items():
                if key in prompt:
                    out.append(float(ppl))
                    break
            else:
                out.append(1.0 + (zlib.crc32(prompt.encode()) % 10000) / 100.0)
        return out

    def get_token_len(self, prompt: str) -> int:
        return max(1, len(str(prompt).split()))

    def get_choice_logprobs(self, inputs, choices):
        """Deterministic prob vectors: canned_ppls keys act as (prompt
        substring → preferred choice index via lowest pseudo-PPL)."""
        out = []
        for prompt in inputs:
            scores = [
                1.0 / self.get_ppl([f'{prompt} {choice}'])[0]
                for choice in choices
            ]
            total = sum(scores)
            out.append([s / total for s in scores])
        return out
