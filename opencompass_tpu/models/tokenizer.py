"""Tokenizer loading with a hermetic fallback.

The reference always has network access to pull HF tokenizers (reference
opencompass/models/huggingface.py:68-95).  This environment may not, so:
try `transformers.AutoTokenizer` from a local path / cache first, and fall
back to a deterministic byte-level tokenizer so every pipeline (tests, bench,
dry runs) works offline.  All tokenization is host-side — token ids are the
only thing shipped to the TPU (SURVEY.md §7 hard part (d)).
"""
from __future__ import annotations

import os
from typing import List, Optional

from opencompass_tpu.utils.logging import get_logger

logger = get_logger()


class ByteTokenizer:
    """UTF-8 byte tokenizer: ids 0..255 = bytes, then specials.

    Deterministic, reversible, zero-asset — the hermetic stand-in for a real
    BPE vocab.  vocab_size defaults to 512 so tiny test models can share it.
    """

    def __init__(self, vocab_size: int = 512):
        assert vocab_size >= 259
        self.vocab_size = vocab_size
        self.pad_token_id = 256
        self.bos_token_id = 257
        self.eos_token_id = 258

    def encode(self, text: str, add_bos: bool = False) -> List[int]:
        ids = list(text.encode('utf-8'))
        return [self.bos_token_id] + ids if add_bos else ids

    def decode(self, ids) -> str:
        data = bytes(i for i in ids if int(i) < 256)
        return data.decode('utf-8', errors='ignore')

    def __call__(self, text: str):
        return {'input_ids': self.encode(text)}


class TokenizerAdapter:
    """Uniform surface over HF tokenizers and ByteTokenizer: ``encode``,
    ``decode``, ``pad_token_id``, ``eos_token_id``, ``vocab_size``."""

    def __init__(self, inner, kind: str):
        self.inner = inner
        self.kind = kind
        if kind == 'hf':
            self.eos_token_id = inner.eos_token_id
            pad = inner.pad_token_id
            self.pad_token_id = pad if pad is not None else \
                (self.eos_token_id if self.eos_token_id is not None else 0)
            self.bos_token_id = getattr(inner, 'bos_token_id', None)
            self.vocab_size = len(inner)
        else:
            self.eos_token_id = inner.eos_token_id
            self.pad_token_id = inner.pad_token_id
            self.bos_token_id = inner.bos_token_id
            self.vocab_size = inner.vocab_size

    def encode(self, text: str, add_special_tokens: bool = False
               ) -> List[int]:
        if self.kind == 'hf':
            return self.inner.encode(text,
                                     add_special_tokens=add_special_tokens)
        return self.inner.encode(text, add_bos=add_special_tokens)

    def decode(self, ids) -> str:
        if self.kind == 'hf':
            return self.inner.decode(ids, skip_special_tokens=True)
        return self.inner.decode(ids)


def load_tokenizer(path: Optional[str],
                   tokenizer_kwargs: Optional[dict] = None,
                   vocab_size: int = 512) -> TokenizerAdapter:
    """AutoTokenizer if resolvable locally, else ByteTokenizer."""
    if path and (os.path.isdir(path) or not path.startswith('byte')):
        try:
            from transformers import AutoTokenizer
            tok = AutoTokenizer.from_pretrained(
                path, local_files_only=True, trust_remote_code=False,
                **(tokenizer_kwargs or {}))
            return TokenizerAdapter(tok, 'hf')
        except Exception as exc:  # offline / missing vocab
            logger.warning(
                f'AutoTokenizer({path!r}) unavailable ({exc}); '
                'falling back to ByteTokenizer')
    return TokenizerAdapter(ByteTokenizer(vocab_size), 'byte')
