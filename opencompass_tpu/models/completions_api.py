"""Text-completions API model with logprob-based PPL.

Parity: reference openicl/utils/api_service.py:1-104 — standalone
OPT-175B / GPT-3 helpers (``api_get_ppl`` via ``echo=True, max_tokens=0``
logprobs, ``api_get_tokens`` completions) that no other reference module
imports.  Here the same measurements are a first-class model wrapper over
any OpenAI-compatible ``/v1/completions`` endpoint, so API-served base
models can run BOTH eval modes — free-form generation and PPL ranking —
through the standard inferencers (the chat wrapper, models/openai_api.py,
can only generate).
"""
from __future__ import annotations

import json
import os
import time
import urllib.error
import urllib.request
from concurrent.futures import ThreadPoolExecutor
from typing import Dict, List, Optional, Union

from opencompass_tpu.registry import MODELS
from opencompass_tpu.utils.logging import get_logger
from opencompass_tpu.utils.prompt import PromptList

from .base_api import BaseAPIModel

PromptType = Union[PromptList, str]

logger = get_logger()


@MODELS.register_module()
class CompletionsAPI(BaseAPIModel):
    """Args:
        path: model name sent in the request body.
        url: completions endpoint (e.g. 'http://host:8000/v1/completions').
        key: bearer token, or 'ENV' to read OPENAI_API_KEY ('' = no auth).
        query_per_second / retry: rate limiting and retry budget.
    """

    is_api = True

    def __init__(self,
                 path: str,
                 url: str,
                 max_seq_len: int = 2048,
                 query_per_second: int = 1,
                 retry: int = 2,
                 key: str = 'ENV',
                 meta_template: Optional[Dict] = None,
                 temperature: Optional[float] = None,
                 generation_kwargs: Optional[Dict] = None):
        super().__init__(path=path,
                         max_seq_len=max_seq_len,
                         meta_template=meta_template,
                         query_per_second=query_per_second,
                         retry=retry,
                         generation_kwargs=generation_kwargs)
        self.url = url
        self.key = os.environ.get('OPENAI_API_KEY', '') if key == 'ENV' \
            else key
        self.temperature = temperature

    # -- transport ---------------------------------------------------------

    def _post(self, body: Dict) -> Dict:
        headers = {'Content-Type': 'application/json'}
        if self.key:
            headers['Authorization'] = f'Bearer {self.key}'
        for attempt in range(self.retry + 1):
            self.wait()
            try:
                request = urllib.request.Request(
                    self.url, data=json.dumps(body).encode(),
                    headers=headers)
                with urllib.request.urlopen(request, timeout=120) as resp:
                    return json.loads(resp.read())
            except urllib.error.HTTPError as err:
                if err.code == 429:
                    logger.warning('rate limited; backing off')
                    time.sleep(2 ** attempt)
                    continue
                logger.error(f'API error {err.code}: {err.reason}')
            except Exception as exc:  # noqa: BLE001 — network variance
                logger.error(f'API request failed: {exc}')
                time.sleep(1)
        raise RuntimeError(
            f'completions API failed after {self.retry + 1} attempts '
            f'({self.url})')

    # -- BaseModel contract ------------------------------------------------

    def generate(self, inputs: List[PromptType],
                 max_out_len: int = 512) -> List[str]:
        def one(prompt):
            body = {'model': self.path, 'prompt': str(prompt),
                    'max_tokens': max_out_len}
            if self.temperature is not None:
                body['temperature'] = self.temperature
            body.update(self.generation_kwargs)
            data = self._post(body)
            return data['choices'][0]['text']
        with ThreadPoolExecutor() as pool:
            futures = [pool.submit(one, p) for p in inputs]
            try:
                return [f.result() for f in futures]
            except Exception:
                for f in futures:
                    f.cancel()
                raise

    def get_ppl(self,
                inputs: List[str],
                mask_length: Optional[List[int]] = None) -> List[float]:
        """Mean token NLL via echoed prompt logprobs (the reference
        api_get_ppl measurement: ``echo=True, max_tokens=0`` and sum of
        ``token_logprobs`` — reference api_service.py:53-70).  With
        ``mask_length``, the first N tokens' logprobs are excluded."""
        def one(args):
            i, text = args
            body = {'model': self.path, 'prompt': str(text),
                    'max_tokens': 0, 'echo': True, 'logprobs': 0}
            data = self._post(body)
            lp = data['choices'][0]['logprobs']['token_logprobs']
            # the first token has no conditional logprob (null)
            vals = [x for x in lp if x is not None]
            if mask_length is not None:
                skip = mask_length[i] - (len(lp) - len(vals))
                vals = vals[max(skip, 0):]
            if not vals:
                return 0.0
            return -sum(vals) / len(vals)
        with ThreadPoolExecutor() as pool:
            return list(pool.map(one, enumerate(inputs)))
