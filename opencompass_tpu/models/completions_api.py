"""Text-completions API model with logprob-based PPL.

Parity: reference openicl/utils/api_service.py:1-104 — standalone
OPT-175B / GPT-3 helpers (``api_get_ppl`` via ``echo=True, max_tokens=0``
logprobs, ``api_get_tokens`` completions) that no other reference module
imports.  Here the same measurements are a first-class model wrapper over
any OpenAI-compatible ``/v1/completions`` endpoint, so API-served base
models can run BOTH eval modes — free-form generation and PPL ranking —
through the standard inferencers (the chat wrapper, models/openai_api.py,
can only generate).
"""
from __future__ import annotations

import os
from typing import Dict, List, Optional, Union

from opencompass_tpu.registry import MODELS
from opencompass_tpu.utils.logging import get_logger
from opencompass_tpu.utils.prompt import PromptList

from .base_api import BaseAPIModel

PromptType = Union[PromptList, str]

logger = get_logger()


@MODELS.register_module()
class CompletionsAPI(BaseAPIModel):
    """Args:
        path: model name sent in the request body.
        url: completions endpoint (e.g. 'http://host:8000/v1/completions').
        key: bearer token, or 'ENV' to read OPENAI_API_KEY ('' = no auth).
        query_per_second / retry: rate limiting and retry budget.
    """

    is_api = True

    def __init__(self,
                 path: str,
                 url: str,
                 max_seq_len: int = 2048,
                 query_per_second: int = 1,
                 retry: int = 2,
                 key: str = 'ENV',
                 meta_template: Optional[Dict] = None,
                 temperature: Optional[float] = None,
                 generation_kwargs: Optional[Dict] = None,
                 max_inflight: int = 8,
                 hedge_after_s: Optional[float] = None,
                 outbound: Optional[Dict] = None):
        super().__init__(path=path,
                         max_seq_len=max_seq_len,
                         meta_template=meta_template,
                         query_per_second=query_per_second,
                         retry=retry,
                         generation_kwargs=generation_kwargs,
                         max_inflight=max_inflight,
                         hedge_after_s=hedge_after_s,
                         outbound=outbound)
        self.url = url
        self.key = os.environ.get('OPENAI_API_KEY', '') if key == 'ENV' \
            else key
        self.temperature = temperature

    # -- transport ---------------------------------------------------------

    def _auth_headers(self) -> Dict:
        return {'Authorization': f'Bearer {self.key}'} if self.key \
            else {}

    def _post(self, body: Dict) -> Dict:
        return self.post_json(self.url, body,
                              headers=self._auth_headers())

    def _post_once(self, body: Dict, timeout: float = 60.0) -> Dict:
        """One un-retried attempt — the outbound scheduler's
        transport."""
        return self.post_json_once(self.url, body,
                                   headers=self._auth_headers(),
                                   timeout=timeout)

    # -- BaseModel contract ------------------------------------------------
    # generate() is BaseAPIModel's scheduler-driven fan-out; PPL and
    # choice ride the same scheduler below, so EVERY row this model
    # sends — gen, ppl, clp — shares one provider's pacing window,
    # retry budget, and breaker.

    def _generate_one(self, prompt: PromptType, max_out_len: int,
                      timeout: float = 60.0) -> str:
        body = {'model': self.path, 'prompt': str(prompt),
                'max_tokens': max_out_len}
        if self.temperature is not None:
            body['temperature'] = self.temperature
        body.update(self.generation_kwargs)
        data = self._post_once(body, timeout=timeout)
        return data['choices'][0]['text']

    def get_ppl(self,
                inputs: List[str],
                mask_length: Optional[List[int]] = None) -> List[float]:
        """Mean token NLL via echoed prompt logprobs (the reference
        api_get_ppl measurement: ``echo=True, max_tokens=0`` and sum of
        ``token_logprobs`` — reference api_service.py:53-70).

        ``mask_length`` is rejected: those counts come from the client's
        heuristic tokenizer (base_api.get_token_len: words + CJK chars)
        and do not line up with the server's BPE token stream, so masking
        by them would silently skew normalized-PPL scores.
        """
        if mask_length is not None:
            raise NotImplementedError(
                'CompletionsAPI.get_ppl cannot honor mask_length: context '
                'lengths measured by the heuristic client tokenizer do '
                "not map onto the server's BPE logprobs.  Use a PPL "
                'template without normalizing_str for API models.')

        def one(text, timeout):
            vals = self._echo_logprobs(text, timeout=timeout)
            if not vals:
                return 0.0
            return -sum(vals) / len(vals)
        return self.outbound_scheduler().run(list(inputs),
                                             one).values()

    def _echo_logprobs(self, text: str,
                       timeout: float = 60.0) -> List[float]:
        body = {'model': self.path, 'prompt': str(text),
                'max_tokens': 0, 'echo': True, 'logprobs': 0}
        data = self._post_once(body, timeout=timeout)
        lp = data['choices'][0]['logprobs']['token_logprobs']
        # the first token has no conditional logprob (null)
        return [x for x in lp if x is not None]

    def choice(self, inputs: List[str], choices: List[str]) -> List[str]:
        """Exact conditional log prob per choice, server-side tokenization:
        sum_logprobs(input + choice) - sum_logprobs(input) is the answer
        span's log prob regardless of how the heuristic client tokenizer
        would have counted it.  The bare-input term is scored once per
        input, not once per (input, choice) pair."""
        def sum_lp(text, timeout):
            return sum(self._echo_logprobs(text, timeout=timeout))
        sched = self.outbound_scheduler()
        base = sched.run(list(inputs), sum_lp).values()
        full = sched.run([inp + c for inp in inputs for c in choices],
                         sum_lp).values()
        n = len(choices)
        return [choices[max(range(n),
                            key=lambda j: full[i * n + j] - base[i])]
                for i in range(len(inputs))]
