"""JaxLM — the TPU-native model wrapper (the reference's HuggingFaceCausalLM
equivalent, reference opencompass/models/huggingface.py:15-337, rebuilt for
XLA instead of torch.cuda).

Design points (SURVEY.md §7):

- **Bucketed static shapes.** torch tolerates ragged batches; XLA compiles
  per shape.  Sequence lengths round up to power-of-two buckets (multiples
  of 128 above 128, MXU-tile friendly) and batches to power-of-two sizes, so
  a task's batches reuse a handful of compiled executables.  `jax.jit`'s
  shape-keyed cache holds them.
- **Host-side tokenization, device-side everything else.** `get_ppl` is one
  jitted forward + shifted-CE (nn/loss.py); `generate` is one jitted
  prefill + `lax.while_loop` decode (nn/decode.py).  Token counts are cached
  (`get_token_len`) because inferencer truncation loops call it repeatedly
  per prompt shrink (reference icl_gen_inferencer.py:150-183 pattern).
- **Mesh-transparent.** With ``parallel=dict(data=..., model=..., seq=...)``
  the same jitted functions run tensor/data-sharded: params are placed via
  Megatron-style NamedShardings (nn/sharding.py), activations follow
  `with_sharding_constraint`s inside the forward.
"""
from __future__ import annotations

import functools
import hashlib
import os
import time
from collections import OrderedDict
from typing import Dict, List, Optional, Union

import jax
import jax.numpy as jnp
import numpy as np

from jax.sharding import NamedSharding
from jax.sharding import PartitionSpec as P

from opencompass_tpu.nn import (TransformerConfig, beam_generate, forward,
                                greedy_generate, greedy_generate_prefixed,
                                init_params, sequence_nll, shard_params)
from opencompass_tpu.parallel.mesh import MeshSpec, make_mesh, use_mesh
from opencompass_tpu.registry import MODELS
from opencompass_tpu.utils.logging import get_logger
from opencompass_tpu.utils.perf import device_call

from .base import BaseModel, _Lazy
from .tokenizer import load_tokenizer

logger = get_logger()


def _bucket(n: int, lo: int = 32, hi: Optional[int] = None) -> int:
    """Round up to a power of two in [lo, hi]."""
    b = lo
    while b < n:
        b *= 2
    return min(b, hi) if hi else b


@MODELS.register_module()
class JaxLM(BaseModel):
    """A causal LM evaluated through jitted JAX functions.

    Args:
        path: HF checkpoint dir (config.json + shards) or '' for random
            init from ``config`` (hermetic tests / benchmarks).
        config: TransformerConfig, preset name ('llama','opt',...) or dict
            of TransformerConfig fields; required when ``path`` has no
            config.json.
        parallel: mesh axis sizes, e.g. ``dict(data=-1, model=1, seq=1)``.
            Only built when >1 device is visible or sizes demand it.
        dtype: parameter/compute dtype override ('bfloat16' on TPU,
            'float32' for bit-stable CPU tests).
        batch_bucket / seq_bucket_min: shape-bucketing knobs.
    """

    # inferencers may re-pack/reorder batches (length-aware planner,
    # icl/inferencers/schedule.py): per-row outputs are batch-independent
    # here, and fewer distinct (B, S) buckets means fewer XLA compiles
    supports_batch_plan = True

    def __init__(self,
                 path: str = '',
                 max_seq_len: int = 2048,
                 config: Union[TransformerConfig, str, Dict, None] = None,
                 parallel: Optional[Dict] = None,
                 dtype: Optional[str] = None,
                 tokenizer_path: Optional[str] = None,
                 tokenizer_kwargs: Optional[Dict] = None,
                 meta_template: Optional[Dict] = None,
                 generation_kwargs: Optional[Dict] = None,
                 seed: int = 0,
                 tokenizer_only: bool = False,
                 batch_padding: bool = True,
                 quantize: Optional[str] = None,
                 convert_cache: Optional[str] = None,
                 shared_prefix: bool = True,
                 run_cfg: Optional[Dict] = None):
        super().__init__(path=path, max_seq_len=max_seq_len,
                         tokenizer_only=tokenizer_only,
                         meta_template=meta_template,
                         generation_kwargs=generation_kwargs)
        try:
            self.cfg = self._resolve_config(path, config, dtype, max_seq_len)
        except ValueError:
            if not tokenizer_only:
                raise
            self.cfg = None  # token counting needs no model config
        # NOTE: with no local checkpoint/tokenizer this falls back to the
        # deterministic byte tokenizer (512-id floor).  Byte token counts
        # differ from the real tokenizer's (usually ~3-4x more tokens per
        # text), so in tokenizer_only mode the SizePartitioner's cost
        # model sees inflated-but-consistent sizes: task packing stays
        # balanced, absolute size estimates don't transfer to real-vocab
        # runs.
        self.tokenizer = load_tokenizer(
            tokenizer_path or path, tokenizer_kwargs,
            vocab_size=self.cfg.vocab_size if self.cfg else 512)
        if self.eos_token_id is None:
            self.eos_token_id = self.tokenizer.eos_token_id
        # token-id LRU shared by get_token_len and _encode_batch so the
        # truncation loop's counting pass tokenizes each prompt once.
        # Both caches key on a string digest and are bounded: full prompt
        # strings or unbounded growth would pile up GBs over a 100k-sample
        # task (prompts can be KBs each, shrink loops multiply variants).
        self._token_len_cache: 'OrderedDict[bytes, int]' = OrderedDict()
        self._token_ids_cache: 'OrderedDict[bytes, List[int]]' = \
            OrderedDict()
        self._ids_cache_max = 8192
        self._len_cache_max = 1_000_000
        # persisted token-length cache (utils/toklen_cache.py): when the
        # sweep pins a cache root, resumed/retried/sibling tasks start
        # from the lengths a previous process already measured instead
        # of re-tokenizing the dataset.  Text never hits disk — only
        # the 16-byte digests this cache is keyed on.
        from opencompass_tpu.utils import toklen_cache
        self._toklen_dir = toklen_cache.resolve_dir()
        self._toklen_digest = toklen_cache.tokenizer_digest(
            self.tokenizer, tokenizer_path or path)
        if self._toklen_dir:
            self._token_len_cache.update(
                toklen_cache.load(self._toklen_dir, self._toklen_digest))
        self._gen_fn_cache: Dict[tuple, object] = {}
        # (kernel kind, static args, shape bucket) keys already dispatched:
        # an unseen key means jax.jit compiles on this call, so its
        # duration is attributed to PerfCounters.compile_seconds (the obs
        # trace report's first-call-vs-steady device_call split)
        self._dispatched_keys: set = set()
        # shared-prefix prefill reuse: a batch whose prompts share a long
        # common token prefix (fixed few-shot ICE blocks; PPL label
        # variants) prefills it once (nn: forward_shared for scoring,
        # prefill_suffix for generation).  Applied when the batch's
        # common prefix is >= _sp_quantum tokens; the prefix length is
        # rounded DOWN to a multiple of the quantum so jit shape buckets
        # stay bounded.  The quantum is coarse (256) on purpose: every
        # distinct (prefix, suffix) shape pair compiles its own
        # executables, and occasional shape pairs hit pathologically
        # slow XLA compiles (measured 10-16 min through the remote-
        # compile tunnel at 7B) — fewer pairs, fewer rolls of that die.
        # Off for prefix-LM / ALiBi models and seq/model meshes.
        self.shared_prefix = shared_prefix
        self._sp_quantum = 256
        # quantize modes compose 'base[-kvN]': base 'int8' (weight-only),
        # 'w8a8' (int8 weights + dynamic per-token int8 activations on
        # the MXU), or 'w4a8' (int4 weights packed two-per-uint8 with
        # 128-wide group scales, unpacked inside the jit — nn/quant.py
        # int4x2 — + int8 activations); '-kv'/'-kv8' adds an int8 decode
        # KV cache, '-kv4' an int4 one.  'w8a8-kv8' is the accuracy-
        # pinned serving recipe (int8 KV rides the Pallas decode kernel
        # on TPU); 'w8a8-kv4'/'w4a8-kv4' halve the cache/decode weight
        # stream again (group-RTN int4: check the agreement probe for
        # your model before trusting scores).
        base, dash, kv = (quantize or '').partition('-')
        if quantize is not None and (
                base not in ('int8', 'w8a8', 'w4a8') or
                (dash and kv not in ('kv', 'kv8', 'kv4'))):
            raise ValueError(f'unsupported quantize={quantize!r} '
                             "(want 'int8'|'w8a8'|'w4a8' optionally + "
                             "'-kv8'|'-kv4', e.g. 'w8a8-kv4')")
        self.quantize = quantize
        self._weight_mode = 'int4x2' if base == 'w4a8' else 'int8'
        if base == 'w4a8' and abs((parallel or {}).get('model', 1)) != 1:
            raise NotImplementedError(
                'w4a8 packed weights are stored NT and do not yet carry '
                'tensor-parallel sharding specs; use model=1 or w8a8')
        if quantize and self.cfg is not None:
            import dataclasses
            updates = {}
            if kv:
                updates['kv_quant'] = 'int4' if kv == 'kv4' else 'int8'
            if base in ('w8a8', 'w4a8'):
                updates['act_quant'] = True
            if updates:
                self.cfg = dataclasses.replace(self.cfg, **updates)
        self.convert_cache = convert_cache
        self.mesh = None
        self.params = None
        if not tokenizer_only:
            self._load_params(path, seed)
            self._maybe_shard(parallel)

    # -- setup -------------------------------------------------------------

    def _resolve_config(self, path, config, dtype, max_seq_len
                        ) -> Optional[TransformerConfig]:
        import dataclasses
        if isinstance(config, TransformerConfig):
            cfg = config
        elif isinstance(config, str):
            cfg = getattr(TransformerConfig, config)()
        elif isinstance(config, dict):
            kw = dict(config)
            preset = kw.pop('preset', None)
            if preset:
                # call the preset with the overrides (NOT replace() on a
                # built default) so derived fields — head_dim,
                # num_kv_heads, intermediate_size — are recomputed from
                # the overridden sizes
                cfg = getattr(TransformerConfig, preset)(**kw)
            else:
                cfg = TransformerConfig(**kw)
        elif path and os.path.isfile(os.path.join(path, 'config.json')):
            from opencompass_tpu.nn.hf_convert import load_hf_config
            cfg = TransformerConfig.from_hf_config(load_hf_config(path))
        else:
            raise ValueError('JaxLM needs `config` or a checkpoint path '
                             'with config.json')
        if dtype:
            cfg = dataclasses.replace(cfg, dtype=dtype)
        if cfg.max_seq_len < max_seq_len:
            cfg = dataclasses.replace(cfg, max_seq_len=max_seq_len)
        return cfg

    def _load_params(self, path: str, seed: int):
        from opencompass_tpu.nn.sat_convert import is_sat_checkpoint
        if is_sat_checkpoint(path):
            # GLM-130B-style SAT model-parallel shards (nn/sat_convert.py)
            from opencompass_tpu.nn.sat_convert import \
                convert_sat_checkpoint_cached
            self.cfg, self.params = convert_sat_checkpoint_cached(
                path, self.cfg, cache_dir=self.convert_cache)
            logger.info(f'loaded SAT checkpoint from {path}')
            if self.quantize:
                from opencompass_tpu.nn.quant import quantize_params
                self.params = quantize_params(self.params, self.cfg,
                                              mode=self._weight_mode)
            return
        has_ckpt = path and os.path.isdir(path) and any(
            f.endswith(('.safetensors', '.bin')) for f in os.listdir(path))
        if has_ckpt:
            from opencompass_tpu.nn.hf_convert import \
                convert_checkpoint_cached
            # stays host numpy: _maybe_shard places shards directly, so the
            # full model never has to fit on a single chip
            self.cfg, self.params = convert_checkpoint_cached(
                path, self.cfg, cache_dir=self.convert_cache)
            logger.info(f'loaded checkpoint from {path}')
            if self.quantize:
                # host-side: only the int8 tensors ever reach a chip
                from opencompass_tpu.nn.quant import quantize_params
                self.params = quantize_params(self.params, self.cfg,
                                              mode=self._weight_mode)
        elif jax.process_count() > 1:
            if path:
                logger.warning(f'no weights under {path!r}; random init '
                               f'(seed={seed})')
            # host-side init: every process derives the identical pytree
            # from the seed, then contributes its local shards.  (Must be a
            # *local* device — jax.devices()[0] may belong to rank 0.)
            with jax.default_device(jax.local_devices(backend='cpu')[0]):
                self.params = init_params(self.cfg, jax.random.PRNGKey(seed))
            if self.quantize:
                from opencompass_tpu.nn.quant import quantize_params
                self.params = jax.tree_util.tree_map(np.asarray,
                                                     self.params)
                self.params = quantize_params(self.params, self.cfg,
                                              mode=self._weight_mode)
        else:
            if path:
                logger.warning(f'no weights under {path!r}; random init '
                               f'(seed={seed})')
            if self.quantize and self._weight_mode == 'int4x2':
                # direct packed init: the fused init+quantize below needs
                # the full bf16 stack as the pack's input, which exceeds
                # HBM for the geometries w4a8 exists to serve (13B-class
                # on one 16 GB chip) — see nn/quant.init_packed_params
                from opencompass_tpu.nn.quant import init_packed_params
                cfg = self.cfg
                self.params = jax.jit(
                    lambda key: init_packed_params(cfg, key))(
                        jax.random.PRNGKey(seed))
            elif self.quantize:
                # ONE fused program: the bf16 weights are scheduler temps
                # freed as each int8 consumer runs, so init+quantize of a
                # near-HBM-sized model fits without fragmentation (a
                # sequence of per-leaf donations fragments the allocator;
                # host init is minutes-slow at 7B)
                from opencompass_tpu.nn.quant import quantize_params
                cfg = self.cfg
                mode = self._weight_mode
                self.params = jax.jit(
                    lambda key: quantize_params(init_params(cfg, key),
                                                cfg, mode=mode))(
                                                    jax.random.PRNGKey(seed))
            else:
                self.params = init_params(self.cfg,
                                          jax.random.PRNGKey(seed))

    def _maybe_shard(self, parallel: Optional[Dict]):
        n_dev = len(jax.devices())
        parallel = parallel or {}
        want = max(1, abs(parallel.get('model', 1)) *
                   abs(parallel.get('seq', 1)))
        if n_dev == 1 and want <= 1:
            # no mesh: commit host (checkpoint) params to the device once,
            # rather than re-uploading per jitted call
            self.params = jax.tree_util.tree_map(jnp.asarray, self.params)
            return
        if parallel.get('seq', 1) > 1 and self.cfg is not None \
                and self.cfg.positional == 'alibi':
            raise ValueError('ring attention (seq>1) does not support '
                             'ALiBi models yet; use data/model axes')
        spec = MeshSpec(data=parallel.get('data', -1),
                        model=parallel.get('model', 1),
                        seq=parallel.get('seq', 1))
        self.mesh = make_mesh(spec)
        self.params = shard_params(self.params, self.cfg, self.mesh)
        shape = dict(zip(self.mesh.axis_names, self.mesh.devices.shape))
        logger.info(f'mesh: {shape}')

    # -- multi-host array plumbing -----------------------------------------

    def _multihost(self) -> bool:
        return self.mesh is not None and jax.process_count() > 1

    def _put(self, arr, spec: P):
        """Host array -> device array.  Across hosts every process holds the
        same full batch; each contributes the shards its devices own."""
        if not self._multihost():
            return jnp.asarray(arr)
        from opencompass_tpu.parallel.distributed import make_global_array
        return make_global_array(arr, NamedSharding(self.mesh, spec))

    def _replicate(self, x):
        """Inside-jit constraint making an output fully replicated, so every
        host can read it without cross-process gathers afterwards."""
        if not self._multihost():
            return x
        return jax.lax.with_sharding_constraint(
            x, NamedSharding(self.mesh, P()))

    # -- jitted kernels (cached per static config) -------------------------

    @functools.cached_property
    def _ppl_fn(self):
        cfg = self.cfg
        mesh = self.mesh
        use_ring = mesh is not None and mesh.shape.get('seq', 1) > 1
        if use_ring:
            if cfg.prefix_lm:
                raise ValueError('prefix-LM scoring is not supported with '
                                 'sequence parallelism (ring attention is '
                                 'causal-blocked); use a data/model mesh')
            from opencompass_tpu.parallel.ring_attention import ring_forward

            @jax.jit
            def ppl(params, tokens, mask, mask_length):
                logits = ring_forward(params, cfg, tokens, mask, mesh)
                return self._replicate(
                    sequence_nll(logits, tokens, mask, mask_length))
            return ppl

        @jax.jit
        def ppl(params, tokens, mask, mask_length):
            prefix_mask = None
            if cfg.prefix_lm:
                # scoring batches are right-padded, so the first
                # mask_length[i] slots are the bidirectional context
                pos = jnp.arange(tokens.shape[1])[None, :]
                prefix_mask = pos < mask_length[:, None]
            logits = forward(params, cfg, tokens, mask,
                             prefix_mask=prefix_mask)
            return self._replicate(
                sequence_nll(logits, tokens, mask, mask_length))
        return ppl

    def _gen_fn(self, max_new: int, temperature: float, top_k: int,
                num_beams: int = 1, length_penalty: float = 1.0,
                prefixed: bool = False):
        # per-instance cache (a class-level lru_cache would pin `self` — and
        # its multi-GB param pytree — alive across model swaps)
        key = (max_new, temperature, top_k, num_beams, length_penalty,
               prefixed)
        fn = self._gen_fn_cache.get(key)
        if fn is not None:
            return fn
        cfg = self.cfg
        eos = self.eos_token_id
        pad = self.tokenizer.pad_token_id or 0

        if prefixed:
            @jax.jit
            def gen(params, prefix, tokens, mask, rng):
                out = greedy_generate_prefixed(
                    params, cfg, prefix, tokens, mask, max_new,
                    eos_token_id=eos, pad_token_id=pad,
                    temperature=temperature, top_k=top_k, rng=rng)
                return jax.tree_util.tree_map(self._replicate, out)
            self._gen_fn_cache[key] = gen
            return gen

        @jax.jit
        def gen(params, tokens, mask, rng):
            if num_beams > 1:
                # beam search is deterministic: rng unused (reference
                # glm.py:166-285 BeamSearchStrategy semantics)
                out = beam_generate(params, cfg, tokens, mask, max_new,
                                    num_beams=num_beams,
                                    eos_token_id=eos, pad_token_id=pad,
                                    length_penalty=length_penalty)
            else:
                out = greedy_generate(params, cfg, tokens, mask, max_new,
                                      eos_token_id=eos, pad_token_id=pad,
                                      temperature=temperature,
                                      top_k=top_k, rng=rng)
            return jax.tree_util.tree_map(self._replicate, out)
        self._gen_fn_cache[key] = gen
        return gen

    def _first_dispatch(self, kind: str, *key_parts) -> bool:
        """True the first time a (kind, static-arg, shape-bucket) key is
        dispatched — the call that pays XLA compilation."""
        key = (kind,) + key_parts
        if key in self._dispatched_keys:
            return False
        self._dispatched_keys.add(key)
        return True

    @functools.cached_property
    def shape_signature(self) -> Optional[str]:
        """Model identity for the compile-cache shape manifest: configs
        producing the same signature compile the same executables for a
        given (kind, B, S), so `cli plan --cache-dir` can join planned
        shapes against shapes a previous run already compiled."""
        if self.cfg is None:
            return None
        import dataclasses
        ident = (dataclasses.asdict(self.cfg), self.quantize,
                 self.max_seq_len)
        return hashlib.blake2b(repr(ident).encode('utf-8'),
                               digest_size=8).hexdigest()

    def _note_compile(self, kind: str, shape, seconds: float):
        """Record a first-dispatched shape bucket (and its observed
        first-call seconds) into the persistent cache's sidecar shape
        manifest.  Never raises; no-op without a cache dir."""
        try:
            from opencompass_tpu.utils import compile_cache
            sig = self.shape_signature
            if sig:
                compile_cache.record_shape(sig, kind, shape, seconds)
        except Exception:
            pass

    def _gen_params(self) -> tuple:
        """(temperature, top_k, seed, num_beams, length_penalty) resolved
        from ``generation_kwargs`` — the static half of the gen-fn cache
        key, shared by :meth:`generate_async` and :meth:`warm_up` so a
        warmed shape is exactly the shape the run dispatches."""
        gk = dict(self.generation_kwargs)
        if gk.get('do_sample', False):
            temperature = float(gk.get('temperature', 1.0))  # HF default
        else:
            temperature = 0.0  # greedy
        return (temperature, int(gk.get('top_k', 0)),
                int(gk.get('seed', 0)), int(gk.get('num_beams', 1)),
                float(gk.get('length_penalty', 1.0)))

    def warm_up(self, specs: List[Dict]) -> int:
        """Pre-compile the planned (B, S_bucket) set before the first
        real batch: each spec is ``{kind: 'ppl'|'gen'|'choice', b, s[,
        max_out_len]}`` (the planner's shape census).  Dispatches one
        dummy batch per unseen bucket through the same jitted functions
        and ``_first_dispatch`` keys the real calls use, so compile time
        lands in one visible warm-up span (and in the persistent cache)
        instead of stalling mid-run.  Shared-prefix variants are not
        warmed (their shapes depend on batch content); those still
        compile lazily.  Returns the number of buckets compiled."""
        if self.tokenizer_only or self.params is None:
            return 0
        pad = self.tokenizer.pad_token_id or 0
        temperature, top_k, seed, num_beams, length_penalty = \
            self._gen_params()
        warmed = 0
        with use_mesh(self.mesh):
            for spec in specs:
                try:
                    kind = spec['kind']
                    max_new = int(spec.get('max_out_len') or 0)
                    # gen batches pad under a decode-reserved cap
                    # (max_seq_len - max_out_len, matching
                    # generate_async); re-bucketing a census shape
                    # without it would round a clamped S back up and
                    # compile an executable the run never dispatches
                    max_len = max(self.max_seq_len - max_new, 32) \
                        if kind == 'gen' else None
                    B, S = self.plan_shape(int(spec['b']),
                                           int(spec['s']), max_len)
                    cs0 = self.perf.compile_seconds
                    spec_arrs = P('data', None)
                    tokens = self._put(np.full((B, S), pad, np.int32),
                                       spec_arrs)
                    mask = self._put(np.ones((B, S), bool), spec_arrs)
                    if kind == 'ppl':
                        if not self._first_dispatch('ppl', False, (B, S)):
                            continue
                        with device_call(self.perf, first=True):
                            out = self._ppl_fn(
                                self.params, tokens, mask,
                                self._put(np.zeros((B,), np.int32),
                                          P('data')))
                            jax.block_until_ready(out)
                    elif kind == 'choice':
                        if not self._first_dispatch('choice', (B, S)):
                            continue
                        with device_call(self.perf, first=True):
                            out = self._choice_logits_fn(self.params,
                                                         tokens, mask)
                            jax.block_until_ready(out)
                    elif kind == 'gen':
                        if not max_new:
                            # unknown decode length = unknown jit key; a
                            # guessed warm-up would compile a shape the
                            # run never dispatches (pure waste at 7B)
                            continue
                        if not self._first_dispatch(
                                'gen', False, (B, S), max_new,
                                temperature, top_k, num_beams,
                                length_penalty):
                            continue
                        fn = self._gen_fn(max_new, temperature, top_k,
                                          num_beams, length_penalty)
                        rng = self._put(jax.random.PRNGKey(seed), P())
                        with device_call(self.perf, first=True):
                            out = fn(self.params, tokens, mask, rng)
                            jax.block_until_ready(out)
                    else:
                        continue
                    warmed += 1
                    self._note_compile(kind, (B, S),
                                       self.perf.compile_seconds - cs0)
                except Exception as exc:
                    logger.warning(
                        f'warm-up of {spec} failed (will compile '
                        f'lazily): {exc}')
        return warmed

    def save_caches(self):
        """Persist the token-length cache for successor processes (the
        task layer calls this when a model's datasets finish)."""
        if self._toklen_dir and self._token_len_cache:
            from opencompass_tpu.utils import toklen_cache
            toklen_cache.save(self._toklen_dir, self._toklen_digest,
                              self._token_len_cache)

    # -- BaseModel contract ------------------------------------------------

    @staticmethod
    def _cache_key(text: str) -> bytes:
        return hashlib.blake2b(text.encode('utf-8'),
                               digest_size=16).digest()

    def _encode_ids(self, text: str) -> List[int]:
        """Tokenize with the tokenizer's own specials (BOS for llama-family
        HF tokenizers), matching the reference's HF-default tokenization
        (reference models/huggingface.py:142,181,262).  Cached: truncation
        loops re-count the same shrinking prompts (ADVICE r1)."""
        key = self._cache_key(text)
        ids = self._token_ids_cache.get(key)
        if ids is None:
            ids = self.tokenizer.encode(text, add_special_tokens=True)
            self._token_ids_cache[key] = ids
            if len(self._token_ids_cache) > self._ids_cache_max:
                self._token_ids_cache.popitem(last=False)
            self._token_len_cache[key] = len(ids)
            if len(self._token_len_cache) > self._len_cache_max:
                self._token_len_cache.popitem(last=False)
        else:
            self._token_ids_cache.move_to_end(key)
        return ids

    def get_token_len(self, prompt: str) -> int:
        prompt = str(prompt)
        n = self._token_len_cache.get(self._cache_key(prompt))
        if n is None:
            n = len(self._encode_ids(prompt))
        return n

    @staticmethod
    def _common_prefix_len(ids: List[List[int]]) -> int:
        """Longest common token prefix across the batch's id rows."""
        if len(ids) < 2:
            return 0
        n = len(ids[0])
        for row in ids[1:]:
            m = min(n, len(row))
            i = 0
            while i < m and row[i] == ids[0][i]:
                i += 1
            n = i
            if n == 0:
                break
        return n

    @property
    def shared_prefix_active(self) -> bool:
        """True when the shared-prefix machinery can structurally engage
        for this model (flag on, compatible config, no blocking mesh).
        Inferencers consult this before reshaping their batches around
        it — with it False, item-major PPL batching would shrink batches
        to len(labels) rows of plain forwards for no benefit."""
        mesh_ok = self.mesh is None or (
            not self._multihost()
            and self.mesh.shape.get('model', 1) == 1
            and self.mesh.shape.get('seq', 1) == 1)
        return bool(self.shared_prefix and mesh_ok
                    and self.cfg is not None and not self.cfg.prefix_lm
                    and self.cfg.positional != 'alibi')

    def _shared_prefix_split(self, ids: List[List[int]],
                             require_dominant: bool = False):
        """(prefix ids, suffix id rows) when the shared-prefix path
        applies to this batch, else (None, ids).  The prefix is rounded
        down to a _sp_quantum multiple (bounded jit shapes) and capped
        so every row keeps at least one suffix token.

        ``require_dominant``: engage only when the prefix is at least as
        long as the padded suffix bucket.  The scoring path's two-source
        attention materializes its score tensors (no flash kernel), so
        with a LONG suffix it loses to the plain flash forward — at 7B,
        label-outer MMLU batches (prefix 1280, suffix bucket 1024)
        measured 4.97 samples/s shared vs 6.52 plain, while
        short-suffix batches measured 2-3x wins.  get_ppl requires
        dominance; generate does not (prefill savings measured to win
        there even at long suffixes)."""
        if not self.shared_prefix_active or len(ids) < 2:
            return None, ids
        cp = self._common_prefix_len(ids)
        cap = min(len(r) for r in ids) - 1
        P = (min(cp, cap) // self._sp_quantum) * self._sp_quantum
        if P < self._sp_quantum:
            return None, ids
        if require_dominant:
            # mirror _pad_ids' bucket cap, else a round-up past
            # max_seq_len declines batches the padder would not pad that
            # far anyway
            s_bucket = _bucket(max(len(r) - P for r in ids),
                               hi=max(self.max_seq_len, 32))
            if P < s_bucket:
                return None, ids
        return ids[0][:P], [row[P:] for row in ids]

    def _encode_batch(self, inputs: List[str], left_pad: bool,
                      max_len: int, keep: str = 'head') -> tuple:
        """Tokenize + bucket-pad.  Returns (tokens, mask) int32/bool arrays
        of shape (bucket_batch, bucket_len).  ``keep`` picks which end
        survives truncation: 'head' (HF-parity default) or 'tail' (for
        scoring at the prompt end, e.g. CLP)."""
        ids = [self._encode_ids(str(s)) for s in inputs]
        ids = [(row[:max_len] if keep == 'head' else row[-max_len:])
               for row in ids]
        tokens, mask = self._pad_ids(ids, left_pad, max_len)
        spec = P('data', None)
        return self._put(tokens, spec), self._put(mask, spec), ids

    def plan_shape(self, n_rows: int, longest: int,
                   max_len: Optional[int] = None) -> tuple:
        """Padded device shape for a batch — the single source of truth
        shared by :meth:`_pad_ids` (what actually ships) and the batch
        planner (what it costs), so the two can never drift."""
        if max_len is None:
            max_len = self.max_seq_len
        S = _bucket(max(int(longest), 1), hi=max(int(max_len), 32))
        min_b = self.mesh.shape.get('data', 1) if self.mesh is not None else 1
        seq_par = self.mesh.shape.get('seq', 1) if self.mesh is not None \
            else 1
        if S % seq_par:  # ring attention shards S over the seq axis
            S = (S // seq_par + 1) * seq_par
        B = _bucket(max(int(n_rows), 1), lo=max(1, min_b))
        if B % min_b:  # non-pow2 data axis
            B = (B // min_b + 1) * min_b
        return B, S

    def _pad_ids(self, ids: List[List[int]], left_pad: bool,
                 max_len: int) -> tuple:
        """Bucket-pad pre-encoded id rows into (tokens, mask) numpy.
        Also charges the padding waste (pad slots actually materialized
        on device) to ``perf.pad_tokens`` — the padding-efficiency
        counter surfaced by the perf table and obs plane."""
        longest = max((len(x) for x in ids), default=1)
        B, S = self.plan_shape(len(ids), longest, max_len)
        self.perf.pad_tokens += B * S - sum(len(row) for row in ids)
        pad_id = self.tokenizer.pad_token_id or 0
        tokens = np.full((B, S), pad_id, np.int32)
        mask = np.zeros((B, S), bool)
        for i, row in enumerate(ids):
            if left_pad:
                tokens[i, S - len(row):] = row
                mask[i, S - len(row):] = True
            else:
                tokens[i, :len(row)] = row
                mask[i, :len(row)] = True
        return tokens, mask

    @functools.cached_property
    def _ppl_shared_fn(self):
        cfg = self.cfg

        @jax.jit
        def shared_nll(params, prefix, tokens, mask, ml):
            from opencompass_tpu.nn import shared_prefix_nll
            return shared_prefix_nll(params, cfg, prefix, tokens, mask,
                                     mask_length=ml)
        return shared_nll

    def get_ppl(self,
                inputs: List[str],
                mask_length: Optional[List[int]] = None) -> List[float]:
        return self.get_ppl_async(inputs, mask_length).result()

    def get_ppl_async(self,
                      inputs: List[str],
                      mask_length: Optional[List[int]] = None):
        """Tokenize, pad and enqueue one scoring batch; the returned
        handle's ``result()`` blocks on the device and copies the NLLs
        to host.  JAX dispatch is async, so the caller can prepare the
        next batch while this one executes (double buffering)."""
        with use_mesh(self.mesh):
            ids = [self._encode_ids(str(s))[:self.max_seq_len]
                   for s in inputs]
            prefix, rows = self._shared_prefix_split(ids,
                                                     require_dominant=True)
            ml = np.zeros((max(len(ids), 1),), np.int32)
            if mask_length is not None:
                ml[:len(mask_length)] = np.asarray(mask_length, np.int32)
            tokens, mask = self._pad_ids(rows, left_pad=False,
                                         max_len=self.max_seq_len)
            mlb = np.zeros((tokens.shape[0],), np.int32)
            mlb[:len(ml)] = ml
            first = self._first_dispatch(
                'ppl', prefix is not None and len(prefix), tokens.shape)
            cs0 = self.perf.compile_seconds
            info = self._tl_track('ppl', tokens.shape, first,
                                  sum(len(r) for r in ids))
            td0 = time.perf_counter()
            with device_call(self.perf,
                             tokens_in=sum(len(r) for r in ids),
                             samples=len(inputs), first=first):
                if prefix is not None:
                    spec = P('data', None)
                    nll = self._ppl_shared_fn(
                        self.params,
                        self._put(np.asarray(prefix, np.int32), P(None)),
                        self._put(tokens, spec), self._put(mask, spec),
                        self._put(mlb, P('data')))
                else:
                    spec = P('data', None)
                    nll = self._ppl_fn(self.params,
                                       self._put(tokens, spec),
                                       self._put(mask, spec),
                                       self._put(mlb, P('data')))
            if info is not None:
                info['dispatch_s'] = time.perf_counter() - td0
            if first and prefix is None:
                # shared-prefix executables are batch-content-dependent;
                # only plain-path shapes enter the manifest
                self._note_compile('ppl', tokens.shape,
                                   self.perf.compile_seconds - cs0)
        n = len(inputs)

        def fetch():
            t0 = time.perf_counter()
            out = np.asarray(nll)
            dt = time.perf_counter() - t0
            self.perf.device_seconds += dt
            if info is not None:
                info['fetch_s'] = dt
            return out[:n].tolist()
        return _Lazy(fetch)

    @functools.cached_property
    def _choice_logits_fn(self):
        """Jitted forward returning logits at each sequence's last real
        position (right-padded batch).  Uses ring attention when the mesh
        has a seq axis, same as the PPL path."""
        cfg = self.cfg
        mesh = self.mesh
        use_ring = mesh is not None and mesh.shape.get('seq', 1) > 1
        if use_ring:
            if cfg.prefix_lm:
                raise ValueError('prefix-LM choice scoring is not '
                                 'supported with sequence parallelism '
                                 '(ring attention is causal-blocked); use '
                                 'a data/model mesh')
            from opencompass_tpu.parallel.ring_attention import ring_forward

        @jax.jit
        def last_logits(params, tokens, mask):
            if use_ring:
                logits = ring_forward(params, cfg, tokens, mask, mesh)
            else:
                # prefix-LM (GLM): the whole prompt is bidirectional
                # context when scoring the next-token choice
                prefix = mask if cfg.prefix_lm else None
                logits = forward(params, cfg, tokens, mask,
                                 prefix_mask=prefix)
            last = jnp.maximum(
                jnp.sum(mask.astype(jnp.int32), axis=-1) - 1, 0)
            return self._replicate(jnp.take_along_axis(
                logits, last[:, None, None], axis=1)[:, 0, :])
        return last_logits

    def get_choice_logprobs(self, inputs: List[str],
                            choices: List[str]) -> List[List[float]]:
        """Softmax over the choices' first-token logits at the prompt end
        (the CLP measurement — reference icl_clp_inferencer.py:206-223)."""
        return self.get_choice_logprobs_async(inputs, choices).result()

    def get_choice_logprobs_async(self, inputs: List[str],
                                  choices: List[str]):
        choice_ids = []
        for choice in choices:
            # no specials here: we want the choice's own first token, not BOS
            ids = self.tokenizer.encode(str(choice),
                                        add_special_tokens=False)
            if not ids:
                raise ValueError(f'choice {choice!r} tokenizes to nothing')
            choice_ids.append(ids[0])
        with use_mesh(self.mesh):
            # keep the tail: the choice position is the prompt's end
            tokens, mask, ids = self._encode_batch(
                inputs, left_pad=False, max_len=self.max_seq_len,
                keep='tail')
            first = self._first_dispatch('choice', tokens.shape)
            cs0 = self.perf.compile_seconds
            info = self._tl_track('choice', tokens.shape, first,
                                  sum(len(r) for r in ids))
            td0 = time.perf_counter()
            with device_call(self.perf,
                             tokens_in=sum(len(r) for r in ids),
                             samples=len(inputs), first=first):
                logits = self._choice_logits_fn(self.params, tokens, mask)
            if info is not None:
                info['dispatch_s'] = time.perf_counter() - td0
            if first:
                self._note_compile('choice', tokens.shape,
                                   self.perf.compile_seconds - cs0)
        n = len(inputs)

        def fetch():
            t0 = time.perf_counter()
            logits_h = np.asarray(logits, np.float64)
            dt = time.perf_counter() - t0
            self.perf.device_seconds += dt
            if info is not None:
                info['fetch_s'] = dt
            sub = logits_h[:n][:, choice_ids]
            sub = np.exp(sub - sub.max(axis=-1, keepdims=True))
            sub = sub / sub.sum(axis=-1, keepdims=True)
            return sub.tolist()
        return _Lazy(fetch)

    def generate(self, inputs: List[str], max_out_len: int) -> List[str]:
        return self.generate_async(inputs, max_out_len).result()

    def generate_async(self, inputs: List[str], max_out_len: int):
        if self.mesh is not None and self.mesh.shape.get('seq', 1) > 1 \
                and not getattr(self, '_warned_seq_gen', False):
            self._warned_seq_gen = True
            logger.warning(
                'generation does not use the seq (ring attention) axis; '
                'decode work is replicated across it — size the seq axis '
                'for scoring workloads, or use a data/model-only mesh for '
                'generation tasks')
        temperature, top_k, seed, num_beams, length_penalty = \
            self._gen_params()
        with use_mesh(self.mesh):
            max_prompt = max(self.max_seq_len - max_out_len, 32)
            ids = [self._encode_ids(str(s))[:max_prompt] for s in inputs]
            prefix, rows = (None, ids) if num_beams > 1 \
                else self._shared_prefix_split(ids)
            tokens, mask = self._pad_ids(rows, left_pad=True,
                                         max_len=max_prompt)
            first = self._first_dispatch(
                'gen', prefix is not None and len(prefix), tokens.shape,
                int(max_out_len), temperature, top_k, num_beams,
                length_penalty)
            cs0 = self.perf.compile_seconds
            info = self._tl_track('gen', tokens.shape, first,
                                  sum(len(r) for r in ids))
            td0 = time.perf_counter()
            with device_call(self.perf,
                             tokens_in=sum(len(r) for r in ids),
                             samples=len(inputs), first=first):
                rng = self._put(jax.random.PRNGKey(seed), P())
                if prefix is not None:
                    spec = P('data', None)
                    fn = self._gen_fn(int(max_out_len), temperature,
                                      top_k, prefixed=True)
                    out, lengths = fn(self.params,
                                      self._put(np.asarray(prefix,
                                                           np.int32),
                                                P(None)),
                                      self._put(tokens, spec),
                                      self._put(mask, spec), rng)
                else:
                    spec = P('data', None)
                    fn = self._gen_fn(int(max_out_len), temperature,
                                      top_k, num_beams, length_penalty)
                    out, lengths = fn(self.params,
                                      self._put(tokens, spec),
                                      self._put(mask, spec), rng)
            if info is not None:
                info['dispatch_s'] = time.perf_counter() - td0
            if first and prefix is None:
                self._note_compile('gen', tokens.shape,
                                   self.perf.compile_seconds - cs0)
        n_in = len(inputs)

        def fetch():
            t0 = time.perf_counter()
            out_h = np.asarray(out)
            lengths_h = np.asarray(lengths)
            dt = time.perf_counter() - t0
            self.perf.device_seconds += dt
            decode_tokens = int(lengths_h[:n_in].sum())
            if info is not None:
                # the fused prefill+decode executable gives no on-device
                # split; dispatch_s ≈ trace/compile + enqueue, fetch_s ≈
                # device wall, and the prefill/decode *token* split lets
                # the report reconstruct the cost structure
                info['fetch_s'] = dt
                info['decode_tokens'] = decode_tokens
            self.perf.tokens_out += decode_tokens
            texts = []
            for i in range(n_in):
                n = int(lengths_h[i])
                row = out_h[i, :n]
                if self.eos_token_id is not None:
                    row = row[row != self.eos_token_id]
                texts.append(self.tokenizer.decode(row))
            return texts
        return _Lazy(fetch)
