"""JaxLM — the TPU-native model wrapper (the reference's HuggingFaceCausalLM
equivalent, reference opencompass/models/huggingface.py:15-337, rebuilt for
XLA instead of torch.cuda).

Design points (SURVEY.md §7):

- **Bucketed static shapes.** torch tolerates ragged batches; XLA compiles
  per shape.  Sequence lengths round up to power-of-two buckets (multiples
  of 128 above 128, MXU-tile friendly) and batches to power-of-two sizes, so
  a task's batches reuse a handful of compiled executables.  `jax.jit`'s
  shape-keyed cache holds them.
- **Host-side tokenization, device-side everything else.** `get_ppl` is one
  jitted forward + shifted-CE (nn/loss.py); `generate` is one jitted
  prefill + `lax.while_loop` decode (nn/decode.py).  Token counts are cached
  (`get_token_len`) because inferencer truncation loops call it repeatedly
  per prompt shrink (reference icl_gen_inferencer.py:150-183 pattern).
- **Mesh-transparent.** With ``parallel=dict(data=..., model=..., seq=...)``
  the same jitted functions run tensor/data-sharded: params are placed via
  Megatron-style NamedShardings (nn/sharding.py), activations follow
  `with_sharding_constraint`s inside the forward.
"""
from __future__ import annotations

import collections
import functools
import hashlib
import os
import threading
import time
from collections import OrderedDict
from typing import Callable, Dict, List, Optional, Union

import jax
import jax.numpy as jnp
import numpy as np

from jax.sharding import NamedSharding
from jax.sharding import PartitionSpec as P

from opencompass_tpu.nn import (TransformerConfig, beam_generate, forward,
                                greedy_generate, greedy_generate_prefixed,
                                init_params, paged_generate_step,
                                paged_verify_step, sequence_nll,
                                shard_params)
from opencompass_tpu.parallel.mesh import MeshSpec, make_mesh, use_mesh
from opencompass_tpu.registry import MODELS
from opencompass_tpu.utils.logging import get_logger
from opencompass_tpu.utils.perf import device_call

from .base import BaseModel, _Lazy
from .tokenizer import load_tokenizer

try:
    from opencompass_tpu.obs import devprof as _devprof
except Exception:       # pragma: no cover — obs must never block models
    _devprof = None

logger = get_logger()


def _step_scope(kind: str, **context):
    """Sampled step profiling + OOM forensics around one device call
    (obs/devprof.py); inert when the obs plane is unavailable."""
    if _devprof is None:
        import contextlib
        return contextlib.nullcontext()
    return _devprof.step_scope(kind, **context)


def _bucket(n: int, lo: int = 32, hi: Optional[int] = None) -> int:
    """Round up to a power of two in [lo, hi]."""
    b = lo
    while b < n:
        b *= 2
    return min(b, hi) if hi else b


class _EngineRow:
    """One sequence moving through the continuous engine."""
    __slots__ = ('ids', 'max_new', 'tag', 'emitted', 'kv_len', 'slot',
                 'done', 'retire_seq', 'event', 'interactive',
                 'submit_ts', 'first_token_ts', 'done_ts', 'token_ts',
                 'prefix_tokens', 'on_token', 'cancelled')

    def __init__(self, ids, max_new, tag, interactive=False,
                 on_token=None):
        self.ids = list(ids)
        self.max_new = int(max_new)
        self.tag = tag
        self.emitted: List[int] = []
        self.kv_len = 0
        # prompt tokens served from the radix prefix cache at admission
        # (prefill skipped them entirely)
        self.prefix_tokens = 0
        self.slot: Optional[int] = None
        self.done = False
        self.retire_seq: Optional[int] = None
        self.event = threading.Event()
        self.interactive = interactive
        self.submit_ts = time.perf_counter()
        self.first_token_ts: Optional[float] = None
        self.done_ts: Optional[float] = None
        # one perf_counter stamp per emitted token: consecutive diffs
        # are this row's inter-token latencies (bounded by max_new)
        self.token_ts: List[float] = []
        # per-token emit hook (streaming): called OUTSIDE the engine
        # lock as (row, token_id) right after each token lands; any
        # exception it raises is swallowed by the driver
        self.on_token = on_token
        # cooperative cancel (client disconnect): the driver retires
        # the row and frees its slot/pages at the next step boundary
        self.cancelled = False

    def itl_seconds(self) -> List[float]:
        """Inter-token gaps (len = emitted - 1)."""
        return [b - a for a, b in zip(self.token_ts,
                                      self.token_ts[1:])]


class ContinuousEngine:
    """Slot-based continuous batcher over a paged KV cache.

    A fixed-capacity set of ``slots`` in-flight sequences shares one
    preallocated page pool (nn/paged_kv.py).  Rows join as earlier rows
    retire, prompts prefill in page-sized chunks, and every device call
    is ONE compiled mixed step: a ``(slots, page_size)`` prefill-chunk
    sub-batch plus a ``(slots, 1)`` decode sub-batch, each
    ``lax.cond``-gated so a pure-decode step skips the prefill compute
    at runtime — regardless of the in-flight length mix.  That replaces
    the fixed-shape path's per-``B×S``-bucket executables and its
    short-rows-wait-for-long-rows padding with one resident step, and
    (unlike the legacy two-shape step, kept behind ``mixed_step=False``)
    lets decode-ready rows advance while a co-resident prompt is still
    prefilling: ``stall_slot_steps`` is 0 by construction.  The KV read
    inside the step is either the Pallas ragged-paged-attention kernel
    (``kv_read_path == 'ragged_kernel'``; attention computed in place
    over the pool pages) or the XLA gather fallback — decided host-side
    once at engine build via ``JaxLM.kv_read_path()``.

    Thread model: any number of threads may :meth:`submit` rows (the
    serve data plane joins interactive requests mid-sweep this way);
    whoever calls :meth:`drain` drives device steps — a non-blocking
    driver lock guarantees exactly one stepping thread, and waiters
    whose rows are being carried by someone else's drain just wait on
    their rows' events.  Greedy outputs are per-row deterministic
    regardless of co-residents (each slot's attention spans only its
    own pages, and the batch shape never changes).
    """

    def __init__(self, model: 'JaxLM', slots: int, page_size: int,
                 num_pages: Optional[int] = None):
        from opencompass_tpu.nn.paged_kv import (PageAllocator, PageTable,
                                                 RadixPrefixCache,
                                                 init_page_pool,
                                                 pages_per_seq,
                                                 pool_pages_for)
        self.model = model
        self.cfg = model.cfg
        self.slots = int(slots)
        self.page_size = int(page_size)
        self.max_pages = pages_per_seq(model.max_seq_len, page_size)
        self.num_pages = int(num_pages or pool_pages_for(
            self.slots, model.max_seq_len, page_size))
        self.pool = init_page_pool(self.cfg, self.num_pages, page_size)
        self.alloc = PageAllocator(self.num_pages)
        self.table = PageTable(self.slots, self.max_pages)
        # radix prefix cache (nn/paged_kv.py): trie nodes own refcounted
        # pool pages keyed by page-granular prompt chunks.  The key —
        # (weights identity, tokenizer digest, sampling params) — is
        # recorded for observability; correctness comes from lifetime:
        # the trie lives and dies with THIS engine, and JaxLM rebuilds
        # the engine whenever any key component changes.
        # guarded-by: _lock
        self.prefix: Optional[RadixPrefixCache] = None
        if getattr(model, 'prefix_cache', False):
            self.prefix = RadixPrefixCache(
                self.alloc, page_size,
                key=(model.shape_signature,
                     getattr(model, '_toklen_digest', ''),
                     model._gen_params()))
        # copy-on-write copies queued by admission, applied to the pool
        # by the driver before the next step's dispatch
        # guarded-by: _lock
        self._pending_cow: List[tuple] = []
        self._copy_fn = None
        # guarded-by: _lock
        self._slots: List[Optional[_EngineRow]] = [None] * self.slots
        # guarded-by: _lock
        self._queue: 'collections.deque[_EngineRow]' = collections.deque()
        # priority lane: interactive rows (serve-plane joins) admit
        # ahead of queued sweep rows — a mid-sweep completion never
        # waits behind the sweep's whole prefill backlog for a slot
        # guarded-by: _lock
        self._prio: 'collections.deque[_EngineRow]' = collections.deque()
        self._lock = threading.Lock()         # queue/slots/alloc/stats
        self._driver = threading.Lock()       # one stepping thread
        (self.temperature, self.top_k, self._seed, num_beams,
         _lp) = model._gen_params()
        if num_beams > 1:
            raise ValueError('continuous batching is greedy/sampling '
                             'only (num_beams == 1)')
        self._base_rng = jax.random.PRNGKey(self._seed)
        # donation keeps the pool update in place on accelerators; CPU
        # ignores donation (and warns), so skip it there
        donate = (1,) if jax.default_backend() != 'cpu' else ()
        cfg, ps = self.cfg, self.page_size
        temp, top_k = self.temperature, self.top_k
        self.mixed = bool(getattr(model, 'continuous_mixed_step', True))
        # decided once, host-side, under the model's mesh — the step
        # traces the identical predicate, so this label IS the path
        self.kv_read_path = model.kv_read_path()
        rk = self.kv_read_path == 'ragged_kernel'
        slots = self.slots

        def _step(params, pool, tokens, start, n_new, page_table, rng):
            return paged_generate_step(params, cfg, tokens, start, n_new,
                                       page_table, pool, ps, rng,
                                       temp, top_k, ragged_kernel=rk)

        def _step_mixed(params, pool, pf_tokens, pf_start, pf_n,
                        dc_tokens, dc_start, dc_n, page_table, rng):
            # both sub-batches live in ONE executable; each is cond-
            # gated so a pure-decode step runs no prefill compute (and
            # vice versa).  Slots are disjoint between sub-batches —
            # inactive rows (n == 0) write to the garbage page and
            # their sampled tokens are ignored host-side.
            def pf(pool):
                nxt, pool = paged_generate_step(
                    params, cfg, pf_tokens, pf_start, pf_n, page_table,
                    pool, ps, jax.random.fold_in(rng, 0), temp, top_k,
                    ragged_kernel=rk)
                return nxt.astype(jnp.int32), pool

            def dc(pool):
                nxt, pool = paged_generate_step(
                    params, cfg, dc_tokens, dc_start, dc_n, page_table,
                    pool, ps, jax.random.fold_in(rng, 1), temp, top_k,
                    ragged_kernel=rk)
                return nxt.astype(jnp.int32), pool

            def skip(pool):
                return jnp.zeros((slots,), jnp.int32), pool

            pf_nxt, pool = jax.lax.cond(jnp.any(pf_n > 0), pf, skip,
                                        pool)
            dc_nxt, pool = jax.lax.cond(jnp.any(dc_n > 0), dc, skip,
                                        pool)
            return jnp.where(pf_n > 0, pf_nxt, dc_nxt), pool

        self._step_fn = jax.jit(_step_mixed if self.mixed else _step,
                                donate_argnums=donate)
        # draft-model speculative decoding: decided once at engine
        # build (JaxLM.speculative_active gates on greedy sampling, an
        # un-meshed target and a vocab-matched draft); the engine then
        # compiles TWO extra executables — the draft's propose step (a
        # prefill lane keeping the draft's KV in lockstep plus a
        # k-step greedy scan) and the target's spec step (prefill lane
        # plus a (slots, k+1) teacher-forced verify lane)
        self.spec = bool(getattr(model, 'speculative_active', False))
        self.spec_k = int(getattr(model, 'draft_k', 0)) if self.spec else 0
        self.draft = model.draft_lm() if self.spec else None
        self.draft_pool = None
        self._draft_copy_fn = None
        if self.spec:
            K = self.spec_k
            draft = self.draft
            dcfg = draft.cfg
            self.draft_pool = init_page_pool(dcfg, self.num_pages,
                                             page_size)
            zero_rng = jax.random.PRNGKey(0)    # greedy: rng unused

            def _step_spec(params, pool, pf_tokens, pf_start, pf_n,
                           vf_tokens, vf_start, vf_n, page_table, rng):
                def pf(pool):
                    nxt, pool = paged_generate_step(
                        params, cfg, pf_tokens, pf_start, pf_n,
                        page_table, pool, ps, jax.random.fold_in(rng, 0),
                        temp, top_k, ragged_kernel=rk)
                    return nxt.astype(jnp.int32), pool

                def skip_pf(pool):
                    return jnp.zeros((slots,), jnp.int32), pool

                def vf(pool):
                    return paged_verify_step(
                        params, cfg, vf_tokens, vf_start, vf_n,
                        page_table, pool, ps, ragged_kernel=rk)

                def skip_vf(pool):
                    return jnp.zeros((slots, K + 1), jnp.int32), pool

                pf_nxt, pool = jax.lax.cond(jnp.any(pf_n > 0), pf,
                                            skip_pf, pool)
                vf_out, pool = jax.lax.cond(jnp.any(vf_n > 0), vf,
                                            skip_vf, pool)
                return pf_nxt, vf_out, pool

            def _step_draft(dparams, dpool, pf_tokens, pf_start, pf_n,
                            dc_tok, dc_start, dc_n, page_table):
                # lockstep prefill: the draft's pool mirrors the
                # target's prompt coverage page for page (same page
                # table!), so trie-matched pages are valid draft KV too
                def pf(dpool):
                    _, dpool = paged_generate_step(
                        dparams, dcfg, pf_tokens, pf_start, pf_n,
                        page_table, dpool, ps, zero_rng, 0.0, 0)
                    return dpool

                dpool = jax.lax.cond(jnp.any(pf_n > 0), pf,
                                     lambda p: p, dpool)

                def propose(dpool):
                    def body(carry, _):
                        tok, pos, dpool = carry
                        nxt, dpool = paged_generate_step(
                            dparams, dcfg, tok[:, None], pos, dc_n,
                            page_table, dpool, ps, zero_rng, 0.0, 0)
                        nxt = nxt.astype(jnp.int32)
                        return (nxt, pos + dc_n, dpool), nxt

                    (_, _, dpool), props = jax.lax.scan(
                        body, (dc_tok, dc_start, dpool), None, length=K)
                    return jnp.transpose(props), dpool   # (slots, K)

                def skip(dpool):
                    return jnp.zeros((slots, K), jnp.int32), dpool

                props, dpool = jax.lax.cond(jnp.any(dc_n > 0), propose,
                                            skip, dpool)
                return props, dpool

            self._spec_step_fn = jax.jit(_step_spec,
                                         donate_argnums=donate)
            self._draft_step_fn = jax.jit(_step_draft,
                                          donate_argnums=donate)
        # telemetry (all under self._lock).  Counters are engine-
        # lifetime; per-drain deltas come from snapshot()/stats(since=)
        # so a resident engine's Nth task reports only its own work.
        # The occupancy series is display-only (sparklines) and
        # bounded — a serve daemon's engine decodes for days
        self.steps = 0
        self.prefill_steps = 0
        self.decode_steps = 0
        self.occupancy_sum = 0      # active slots summed over steps
        self.joined = 0
        self.prio_joined = 0        # interactive-lane admissions
        self.retired = 0
        # rows cancelled before natural retirement (client disconnects)
        # guarded-by: _lock
        self.cancelled_rows = 0
        self._retire_seq = 0
        # guarded-by: _lock
        self._occ_series: 'collections.deque[int]' = collections.deque(
            maxlen=4096)
        # decode-ready rows idled by a prefill step, summed over steps:
        # the MEASURED "prefill stalls decode slots" number a mixed
        # prefill+decode step (ROADMAP item 1) would reclaim
        self.stall_slot_steps = 0
        # per-step records (kind, wall, slot composition, retirements)
        # — bounded like the occupancy series; per-drain deltas take
        # the tail.  Schema: {'k': 'm' (mixed) | 'p'|'d' (legacy
        # two-shape) | 's' (speculative draft+verify), 'w': wall_s,
        # 'pf': prefilling rows, 'dc': decoding rows, 'st':
        # decode-ready rows stalled behind the prefill chunk (always 0
        # for mixed and speculative steps), 'ret': retired}
        # guarded-by: _lock
        self._step_records: 'collections.deque[Dict]' = \
            collections.deque(maxlen=4096)
        # roofline accounting (obs/costmodel.py): exact per-engine
        # token/step/attended-position counters so MFU/MBU and the
        # paged-gather-vs-ideal KV-traffic ratio come from what the
        # engine actually did, not an equal-length approximation
        self.device_seconds = 0.0
        self.prefill_tokens = 0
        # tokens processed at decode steps = active rows summed over
        # decode steps = occupancy_sum (already tracked above); no
        # separate counter needed.
        # kv_positions: per step, each active row's current KV extent
        # (start + n_new) — the IDEAL HBM read traffic a ragged kernel
        # would move (one materialization per step, on-chip reuse
        # across the chunk's queries); the gather path's actual is
        # steps * slots * max_pages * page_size.
        # attn_positions: attended (query, key) PAIRS (token i of a
        # chunk starting at s attends s+i+1 positions) — the attention
        # FLOPs input, which unlike bytes scales per query token.
        self.kv_positions = 0
        self.attn_positions = 0
        # page_read_positions: what the ragged kernel actually fetches
        # — page-granular: per executed sub-batch each slot reads
        # ceil(extent / page) pages (inactive slots one clamped page:
        # the kernel's index-map clamp makes repeat pages free but the
        # first fetch is real).  The kernel-path kv_ratio numerator
        # (obs/costmodel.engine_cost kv_read_path='ragged_kernel').
        self.page_read_positions = 0
        # decode tokens actually processed (teacher-forced verify
        # chunks count every scored position).  For the non-spec engine
        # this equals occupancy_sum by construction; with speculation a
        # decode row advances up to k+1 tokens per step.
        # guarded-by: _lock
        self.decode_tokens = 0
        # prefix-cache counters (all under _lock): admissions that
        # matched the trie, prompt tokens whose prefill was skipped,
        # the attended (query, key) pairs those tokens would have cost,
        # and copy-on-write page copies
        # guarded-by: _lock
        self.prefix_hits = 0
        # guarded-by: _lock
        self.prefix_saved_tokens = 0
        # guarded-by: _lock
        self.prefix_saved_attn = 0
        # guarded-by: _lock
        self.prefix_cow_copies = 0
        # speculative-decoding counters: draft proposals scored and
        # accepted (acceptance rate = accepted / proposed per drain)
        # guarded-by: _lock
        self.spec_proposed = 0
        # guarded-by: _lock
        self.spec_accepted = 0
        try:
            from opencompass_tpu.obs.costmodel import CostModel
            self._costmodel = CostModel.for_model(model)
        except Exception:
            self._costmodel = None
        # rate-limit for the structured kv_pool_pressure obs event: an
        # exhausted pool bounces an admission every step, the event
        # stream must not scale with step count
        self._last_pressure_event = 0.0

    # -- intake ------------------------------------------------------------

    def submit(self, ids: List[int], max_new: int, tag=None,
               interactive: bool = False,
               on_token=None) -> _EngineRow:
        """Queue one sequence; it joins the resident step as a slot (and
        enough pool pages) free up.  Raises when the row could never fit
        the pool — callers fall back to the dense path for it."""
        from opencompass_tpu.nn.paged_kv import OutOfPages, pages_per_seq
        need = pages_per_seq(len(ids) + max_new, self.page_size)
        if need > self.max_pages:
            raise ValueError(
                f'row needs {need} pages (> {self.max_pages} per-sequence '
                f'max); prompt + max_new must fit max_seq_len '
                f'({self.model.max_seq_len})')
        if need > self.num_pages - 1:
            raise OutOfPages(
                f'row needs {need} pages but the pool holds '
                f'{self.num_pages - 1}; raise kv_pool_pages')
        row = _EngineRow(ids, max_new, tag, interactive=interactive,
                         on_token=on_token)
        with self._lock:
            (self._prio if interactive else self._queue).append(row)
        return row

    def cancel(self, rows: List[_EngineRow]) -> int:
        """Cancel rows cooperatively (client disconnect): queued rows
        leave their lane immediately; slotted rows are marked and the
        driver retires them — freeing slot and pool pages — at the
        next step boundary.  Each cancelled row's event fires so any
        drainer stops waiting on it.  Returns the number of rows that
        had not already retired."""
        cancelled: List[_EngineRow] = []
        with self._lock:
            wanted = {id(r) for r in rows}
            for lane in (self._prio, self._queue):
                for row in [r for r in lane if id(r) in wanted]:
                    lane.remove(row)
                    row.done = True
                    row.cancelled = True
                    row.retire_seq = self._retire_seq
                    self._retire_seq += 1
                    row.done_ts = time.perf_counter()
                    cancelled.append(row)
            for row in rows:
                if row.slot is not None and not row.done:
                    row.cancelled = True
                    cancelled.append(row)
            self.cancelled_rows += len(cancelled)
        # queued rows retire right here; slotted rows' events fire
        # from the driver once their slot and pages are reclaimed
        for row in cancelled:
            if row.done:
                row.event.set()
        return len(cancelled)

    def pin_prefix(self, ids: List[int]) -> int:
        """Pin ``ids``' cached full-page prefix chain against LRU
        eviction (hot system prompts the serve front door keeps
        seeing).  No-op without a prefix cache; returns newly pinned
        trie nodes."""
        with self._lock:
            if self.prefix is None:
                return 0
            return self.prefix.pin(ids)

    def unpin_prefix(self, ids: List[int]) -> int:
        """Release a :meth:`pin_prefix` shield; returns nodes unpinned."""
        with self._lock:
            if self.prefix is None:
                return 0
            return self.prefix.unpin(ids)

    def _sweep_cancelled_locked(self) -> List[_EngineRow]:
        """Retire slotted rows whose cancel flag is set (caller holds
        ``_lock``); the caller fires their events after release."""
        swept = []
        for row in [r for r in self._slots if r is not None]:
            if row.cancelled:
                self._retire_locked(row)
                swept.append(row)
        return swept

    def _admit_locked(self):
        from opencompass_tpu.nn.paged_kv import OutOfPages, pages_per_seq
        for slot in range(self.slots):
            if self._slots[slot] is not None:
                continue
            # priority lane first: an interactive join takes the next
            # free slot ahead of every queued sweep row (FIFO within
            # each lane)
            lane = self._prio if self._prio else self._queue
            if not lane:
                continue
            row = lane[0]
            total = pages_per_seq(len(row.ids) + row.max_new,
                                  self.page_size)
            # prefix-cache fast path: fully-matched pages map read-only
            # into this slot (one row reference each); a partial match
            # copies its page before any divergent write (COW)
            matched_pages: List[int] = []
            matched = 0
            cow_src = None
            if self.prefix is not None:
                matched_pages, matched, cow_src = \
                    self.prefix.match(row.ids)
            need = total - len(matched_pages)
            try:
                pages = self._alloc_or_evict_locked(need)
            except OutOfPages:
                # FIFO back-pressure: retries next step.  Surface the
                # stall as a structured obs event (rate-limited) so an
                # undersized kv_pool_pages shows up in the event
                # stream instead of only as mysteriously low slot_util
                if matched_pages or cow_src is not None:
                    self.alloc.free(
                        matched_pages
                        + ([cow_src] if cow_src is not None else []))
                self._note_pool_pressure_locked(need)
                break
            if cow_src is not None:
                # pages[0] becomes the COW destination: the driver
                # copies the shared page into it before the next step,
                # and the row's suffix prefill overwrites the divergent
                # tail before any of its queries can attend it
                self._pending_cow.append((cow_src, pages[0]))
                self.prefix_cow_copies += 1
            lane.popleft()
            self.table.assign(slot, matched_pages + pages)
            row.kv_len = matched
            row.prefix_tokens = matched
            if matched:
                self.prefix_hits += 1
                self.prefix_saved_tokens += matched
                # pairs the skipped prefill would have attended:
                # token i attends i + 1 positions
                self.prefix_saved_attn += matched * (matched + 1) // 2
            row.slot = slot
            self._slots[slot] = row
            self.joined += 1
            if row.interactive:
                self.prio_joined += 1

    def _alloc_or_evict_locked(self, need: int) -> List[int]:
        """Allocate ``need`` pages, evicting cold trie pages (LRU,
        trie-only references) to make room before giving up."""
        from opencompass_tpu.nn.paged_kv import OutOfPages
        try:
            return self.alloc.alloc(need)
        except OutOfPages:
            if self.prefix is None:
                raise
            short = need - self.alloc.n_free
            if self.prefix.evict(short) < short:
                raise
            return self.alloc.alloc(need)

    def _note_pool_pressure_locked(self, need: int):
        """One ``kv_pool_pressure`` event per admission-stall episode
        (>= 5 s apart): queued rows waiting on page exhaustion.  Never
        fails a step."""
        now = time.monotonic()
        if now - self._last_pressure_event < 5.0:
            return
        self._last_pressure_event = now
        try:
            from opencompass_tpu.obs import get_tracer
            tracer = get_tracer()
            if tracer.enabled:
                tracer.event('kv_pool_pressure',
                             need_pages=int(need),
                             free_pages=self.alloc.n_free,
                             pool_pages=self.num_pages,
                             queued_rows=(len(self._queue)
                                          + len(self._prio)),
                             failed_allocs=self.alloc.failed_allocs,
                             high_water=self.alloc.high_water)
                tracer.counter('engine.kv_pool_stalls').inc()
        except Exception:
            pass

    def _retire_locked(self, row: _EngineRow):
        self.alloc.free(self.table.clear(row.slot))
        self._slots[row.slot] = None
        row.slot = None
        row.done = True
        row.retire_seq = self._retire_seq
        self._retire_seq += 1
        self.retired += 1
        row.done_ts = time.perf_counter()

    # -- device stepping ---------------------------------------------------

    def _apply_cow(self, pending: List[tuple]):
        """Execute queued copy-on-write page copies (driver thread,
        before the step that first writes into the copies), then drop
        the match's temporary reference on each source page."""
        if not pending:
            return
        if self._copy_fn is None:
            def _copy(pool, src, dst):
                return {k: v.at[:, dst].set(v[:, src])
                        for k, v in pool.items()}
            donate = (0,) if jax.default_backend() != 'cpu' else ()
            self._copy_fn = jax.jit(_copy, donate_argnums=donate)
        model = self.model
        first = model._first_dispatch('page_copy', (1, 1),
                                      self.temperature, self.top_k)
        cs0 = model.perf.compile_seconds
        t0 = time.perf_counter()
        for src, dst in pending:
            s, d = np.int32(src), np.int32(dst)
            with use_mesh(model.mesh):
                self.pool = self._copy_fn(self.pool, s, d)
                if self.draft_pool is not None:
                    self.draft_pool = self._copy_fn(self.draft_pool,
                                                    s, d)
        elapsed = time.perf_counter() - t0
        self.device_seconds += elapsed
        model.perf.device_seconds += elapsed
        model.perf.calls += 1
        if first:
            model.perf.compile_seconds += elapsed
            model.perf.first_calls += 1
            model._note_compile('page_copy', (1, 1),
                                model.perf.compile_seconds - cs0)
        with self._lock:
            self.alloc.free([src for src, _ in pending])

    def _device_step(self) -> bool:
        """One engine step (caller holds the driver lock).  Returns
        False when there was nothing to do."""
        if self.spec:
            return self._device_step_spec()
        model = self.model
        with self._lock:
            swept = self._sweep_cancelled_locked()
        for row in swept:
            row.event.set()
        with self._lock:
            self._admit_locked()
            pending_cow, self._pending_cow = self._pending_cow, []
            active = [r for r in self._slots if r is not None]
            if not active:
                return False
            prefilling = [r for r in active if r.kv_len < len(r.ids)]
            if self.mixed:
                # the mixed step advances BOTH populations at once:
                # prefilling rows take a chunk, decode-ready rows take
                # a token — nobody idles behind head-of-line prefill
                pf_rows = prefilling
                dc_rows = [r for r in active
                           if r.kv_len >= len(r.ids)]
            elif prefilling:
                pf_rows, dc_rows = prefilling, []
            else:
                pf_rows, dc_rows = [], active
            t = self.page_size
            pf_tokens = np.zeros((self.slots, t), np.int32)
            pf_start = np.zeros((self.slots,), np.int32)
            pf_n = np.zeros((self.slots,), np.int32)
            dc_tokens = np.zeros((self.slots, 1), np.int32)
            dc_start = np.zeros((self.slots,), np.int32)
            dc_n = np.zeros((self.slots,), np.int32)
            for row in pf_rows:
                chunk = row.ids[row.kv_len:row.kv_len + t]
                pf_tokens[row.slot, :len(chunk)] = chunk
                pf_start[row.slot] = row.kv_len
                pf_n[row.slot] = len(chunk)
                self.prefill_tokens += len(chunk)
                # ideal HBM reads: this row's KV extent after the
                # chunk, materialized once this step
                self.kv_positions += row.kv_len + len(chunk)
                # attended pairs: token i of a chunk starting at s
                # attends s + i + 1 positions
                self.attn_positions += (len(chunk) * row.kv_len
                                        + len(chunk)
                                        * (len(chunk) + 1) // 2)
            for row in dc_rows:
                dc_tokens[row.slot, 0] = row.emitted[-1]
                dc_start[row.slot] = row.kv_len
                dc_n[row.slot] = 1
                self.kv_positions += row.kv_len + 1
                self.attn_positions += row.kv_len + 1
            # kernel-path actual reads, page-granular per executed
            # sub-batch: each slot fetches ceil(extent / page) pages
            # (>= 1: inactive slots still pull one clamped page)
            for start_a, n_a, ran in ((pf_start, pf_n, bool(pf_rows)),
                                      (dc_start, dc_n, bool(dc_rows))):
                if ran:
                    pages = np.maximum(
                        1, -(-(start_a + n_a) // self.page_size))
                    self.page_read_positions += (int(pages.sum())
                                                 * self.page_size)
            n_new = pf_n + dc_n      # sub-batch slots are disjoint
            page_table = self.table.table.copy()
            self.steps += 1
            step_no = self.steps
            n_active = len(active)
            n_prefill = len(pf_rows)
            n_decode = len(dc_rows)
            # legacy two-shape step: a prefill step advances only
            # prefilling rows; every decode-ready co-resident idles —
            # that head-of-line cost is what the mixed step reclaims
            # (stalled is 0 by construction there)
            stalled = 0 if self.mixed else (
                n_active - n_prefill if pf_rows else 0)
            if pf_rows:
                self.prefill_steps += 1
                self.stall_slot_steps += stalled
            if dc_rows:
                self.decode_steps += 1
                self.occupancy_sum += n_decode
                self.decode_tokens += n_decode
                self._occ_series.append(n_decode)

        self._apply_cow(pending_cow)
        if self.mixed:
            kind, shape = 'mixed', (self.slots, self.page_size + 1)
        elif pf_rows:
            kind, shape = 'prefill_chunk', (self.slots, self.page_size)
        else:
            kind, shape = 'decode', (self.slots, 1)
        first = model._first_dispatch(
            kind, shape, self.temperature, self.top_k)
        cs0 = model.perf.compile_seconds
        t0 = time.perf_counter()
        rng = jax.random.fold_in(self._base_rng, step_no)
        if self.mixed:
            step_args = (model.params, self.pool,
                         jnp.asarray(pf_tokens), jnp.asarray(pf_start),
                         jnp.asarray(pf_n), jnp.asarray(dc_tokens),
                         jnp.asarray(dc_start), jnp.asarray(dc_n),
                         jnp.asarray(page_table), rng)
        else:
            tokens, start = (pf_tokens, pf_start) if pf_rows \
                else (dc_tokens, dc_start)
            step_args = (model.params, self.pool, jnp.asarray(tokens),
                         jnp.asarray(start), jnp.asarray(n_new),
                         jnp.asarray(page_table), rng)
        with use_mesh(model.mesh), \
                _step_scope(kind, site='engine_step', step=step_no,
                            slots=self.slots, page_size=self.page_size):
            nxt, self.pool = self._step_fn(*step_args)
            nxt = np.asarray(nxt)
        elapsed = time.perf_counter() - t0
        self.device_seconds += elapsed
        perf = model.perf
        perf.device_seconds += elapsed
        perf.calls += 1
        if first:
            perf.compile_seconds += elapsed
            perf.first_calls += 1
            # the post-step self.pool has the donated pool's shapes, so
            # the compile audit's AOT re-lower sees the same avals the
            # dispatch above compiled for
            model._note_compile(
                kind, shape, perf.compile_seconds - cs0,
                fn=self._step_fn,
                args=(model.params, self.pool) + step_args[2:],
                extra={'attn_width': self.max_pages * self.page_size,
                       'kv_read_path': self.kv_read_path})

        eos = model.eos_token_id
        retired: List[_EngineRow] = []
        emits: List[tuple] = []
        with self._lock:
            for row in [r for r in self._slots if r is not None]:
                n = int(n_new[row.slot])
                if not n:
                    continue
                row.kv_len += n
                if row.kv_len < len(row.ids):
                    continue        # still prefilling
                tok = int(nxt[row.slot])
                now_tok = time.perf_counter()
                if not row.emitted:
                    row.first_token_ts = now_tok
                    # prefill just finished: donate this row's full
                    # prompt pages to the trie (before any retire can
                    # clear the slot's table row)
                    if self.prefix is not None:
                        self.prefix.insert(
                            row.ids, self.table.pages(row.slot))
                row.token_ts.append(now_tok)
                row.emitted.append(tok)
                if row.on_token is not None:
                    emits.append((row, tok))
                if (eos is not None and tok == eos) \
                        or len(row.emitted) >= row.max_new:
                    self._retire_locked(row)
                    retired.append(row)
            self._step_records.append({
                'k': 'm' if self.mixed else ('p' if pf_rows else 'd'),
                'w': round(elapsed, 6),
                'pf': n_prefill,
                'dc': n_decode,
                'st': stalled,
                'ret': len(retired)})
            self._note_heartbeat_locked()
        # streaming emit hooks fire outside the lock (they do I/O);
        # before the done events so a drainer never beats the last token
        for row, tok in emits:
            try:
                row.on_token(row, tok)
            except Exception:
                pass
        for row in retired:
            row.event.set()
        return True

    def _device_step_spec(self) -> bool:
        """One speculative engine step (caller holds the driver lock):
        the draft proposes ``spec_k`` greedy tokens per decode row
        (after a lockstep prefill keeping its own pool page-identical
        to the target's), the target scores all proposals in ONE
        teacher-forced verify lane, and the host accepts the longest
        agreeing prefix plus the target's bonus token.  Every emitted
        token is a target argmax, so greedy output is token-identical
        to the unspeculated engine by construction; rejected positions'
        stale K/V is overwritten before any later query can attend it.
        Rows within ``spec_k`` tokens of their budget fall back to
        one-token verify chunks (no draft writes past their pages).
        """
        model = self.model
        K = self.spec_k
        with self._lock:
            swept = self._sweep_cancelled_locked()
        for row in swept:
            row.event.set()
        with self._lock:
            self._admit_locked()
            pending_cow, self._pending_cow = self._pending_cow, []
            active = [r for r in self._slots if r is not None]
            if not active:
                return False
            pf_rows = [r for r in active if r.kv_len < len(r.ids)]
            dc_rows = [r for r in active if r.kv_len >= len(r.ids)]
            t = self.page_size
            pf_tokens = np.zeros((self.slots, t), np.int32)
            pf_start = np.zeros((self.slots,), np.int32)
            pf_n = np.zeros((self.slots,), np.int32)
            vf_tokens = np.zeros((self.slots, K + 1), np.int32)
            vf_start = np.zeros((self.slots,), np.int32)
            vf_n = np.zeros((self.slots,), np.int32)
            dc_tok = np.zeros((self.slots,), np.int32)
            dc_n = np.zeros((self.slots,), np.int32)
            for row in pf_rows:
                chunk = row.ids[row.kv_len:row.kv_len + t]
                pf_tokens[row.slot, :len(chunk)] = chunk
                pf_start[row.slot] = row.kv_len
                pf_n[row.slot] = len(chunk)
                self.prefill_tokens += len(chunk)
                self.kv_positions += row.kv_len + len(chunk)
                self.attn_positions += (len(chunk) * row.kv_len
                                        + len(chunk)
                                        * (len(chunk) + 1) // 2)
            for row in dc_rows:
                cap = row.max_new - len(row.emitted)
                n_v = K + 1 if cap >= K + 1 else 1
                vf_tokens[row.slot, 0] = row.emitted[-1]
                vf_start[row.slot] = row.kv_len
                vf_n[row.slot] = n_v
                if n_v > 1:
                    dc_tok[row.slot] = row.emitted[-1]
                    dc_n[row.slot] = 1
                self.kv_positions += row.kv_len + n_v
                self.attn_positions += (n_v * row.kv_len
                                        + n_v * (n_v + 1) // 2)
            for start_a, n_a, ran in ((pf_start, pf_n, bool(pf_rows)),
                                      (vf_start, vf_n, bool(dc_rows))):
                if ran:
                    pages = np.maximum(
                        1, -(-(start_a + n_a) // self.page_size))
                    self.page_read_positions += (int(pages.sum())
                                                 * self.page_size)
            page_table = self.table.table.copy()
            self.steps += 1
            step_no = self.steps
            n_prefill = len(pf_rows)
            n_decode = len(dc_rows)
            if pf_rows:
                self.prefill_steps += 1
            if dc_rows:
                self.decode_steps += 1
                self.occupancy_sum += n_decode
                self._occ_series.append(n_decode)

        self._apply_cow(pending_cow)
        rng = jax.random.fold_in(self._base_rng, step_no)
        t0 = time.perf_counter()
        # draft pass: lockstep prefill + k-token greedy proposal scan
        d_kind, d_shape = 'spec_draft', (self.slots, self.page_size + K)
        d_first = model._first_dispatch(d_kind, d_shape,
                                        self.temperature, self.top_k)
        cs0 = model.perf.compile_seconds
        with _step_scope(d_kind, site='engine_step', step=step_no,
                         slots=self.slots, page_size=self.page_size):
            props, self.draft_pool = self._draft_step_fn(
                self.draft.params, self.draft_pool,
                jnp.asarray(pf_tokens), jnp.asarray(pf_start),
                jnp.asarray(pf_n), jnp.asarray(dc_tok),
                jnp.asarray(vf_start), jnp.asarray(dc_n),
                jnp.asarray(page_table))
            props = np.asarray(props)
        d_el = time.perf_counter() - t0
        if d_first:
            model.perf.compile_seconds += d_el
            model.perf.first_calls += 1
            model._note_compile(d_kind, d_shape,
                                model.perf.compile_seconds - cs0)
        for row in dc_rows:
            n_v = int(vf_n[row.slot])
            if n_v > 1:
                vf_tokens[row.slot, 1:n_v] = props[row.slot, :n_v - 1]
        # target pass: prefill lane + teacher-forced verify lane
        v_kind = 'spec_mixed'
        v_shape = (self.slots, self.page_size + K + 1)
        v_first = model._first_dispatch(v_kind, v_shape,
                                        self.temperature, self.top_k)
        cs0 = model.perf.compile_seconds
        t1 = time.perf_counter()
        step_args = (model.params, self.pool,
                     jnp.asarray(pf_tokens), jnp.asarray(pf_start),
                     jnp.asarray(pf_n), jnp.asarray(vf_tokens),
                     jnp.asarray(vf_start), jnp.asarray(vf_n),
                     jnp.asarray(page_table), rng)
        with use_mesh(model.mesh), \
                _step_scope(v_kind, site='engine_step', step=step_no,
                            slots=self.slots, page_size=self.page_size):
            pf_nxt, vf_out, self.pool = self._spec_step_fn(*step_args)
            pf_nxt = np.asarray(pf_nxt)
            vf_out = np.asarray(vf_out)
        v_el = time.perf_counter() - t1
        elapsed = time.perf_counter() - t0
        self.device_seconds += elapsed
        model.perf.device_seconds += elapsed
        model.perf.calls += 2
        if v_first:
            model.perf.compile_seconds += v_el
            model.perf.first_calls += 1
            model._note_compile(
                v_kind, v_shape, model.perf.compile_seconds - cs0,
                fn=self._spec_step_fn,
                args=(model.params, self.pool) + step_args[2:],
                extra={'attn_width': self.max_pages * self.page_size,
                       'kv_read_path': self.kv_read_path})

        eos = model.eos_token_id
        retired: List[_EngineRow] = []
        emits: List[tuple] = []
        with self._lock:
            for row in [r for r in self._slots if r is not None]:
                if pf_n[row.slot]:
                    row.kv_len += int(pf_n[row.slot])
                    if row.kv_len < len(row.ids):
                        continue        # still prefilling
                    tok = int(pf_nxt[row.slot])
                    now_tok = time.perf_counter()
                    row.first_token_ts = now_tok
                    if self.prefix is not None:
                        self.prefix.insert(
                            row.ids, self.table.pages(row.slot))
                    row.token_ts.append(now_tok)
                    row.emitted.append(tok)
                    if row.on_token is not None:
                        emits.append((row, tok))
                    if (eos is not None and tok == eos) \
                            or len(row.emitted) >= row.max_new:
                        self._retire_locked(row)
                        retired.append(row)
                    continue
                n_v = int(vf_n[row.slot])
                if not n_v:
                    continue
                fed = vf_tokens[row.slot]
                out = vf_out[row.slot]
                # accept the longest prefix of proposals the target's
                # argmax reproduces; output m is the bonus token the
                # target emits after the last accepted proposal
                m = 0
                while m < n_v - 1 and int(fed[m + 1]) == int(out[m]):
                    m += 1
                if n_v > 1:
                    self.spec_proposed += n_v - 1
                    self.spec_accepted += m
                self.decode_tokens += n_v
                row.kv_len += m + 1
                now_tok = time.perf_counter()
                for tok in (int(x) for x in out[:m + 1]):
                    row.token_ts.append(now_tok)
                    row.emitted.append(tok)
                    if row.on_token is not None:
                        emits.append((row, tok))
                    if (eos is not None and tok == eos) \
                            or len(row.emitted) >= row.max_new:
                        self._retire_locked(row)
                        retired.append(row)
                        break
            self._step_records.append({
                'k': 's',
                'w': round(elapsed, 6),
                'pf': n_prefill,
                'dc': n_decode,
                'st': 0,
                'ret': len(retired)})
            self._note_heartbeat_locked()
        for row, tok in emits:
            try:
                row.on_token(row, tok)
            except Exception:
                pass
        for row in retired:
            row.event.set()
        return True

    def _note_heartbeat_locked(self):
        """Live decode-slot utilization, engine-lifetime MFU/MBU, and
        KV-pool occupancy gauges into this task's heartbeat (the status
        plane's ``decode_slot_util`` / ``mbu`` / ``kv_pool_*`` signals,
        folded into status.json and ``oct_run_*`` / ``oct_kv_pool_*``
        on ``/metrics``).  Rate-limited by the heartbeat itself; never
        fails.  Caller holds ``self._lock`` — everything here reads
        counters directly, never via :meth:`stats`."""
        if self.decode_steps and self.decode_steps % 8 == 0:
            try:
                from opencompass_tpu.obs import get_heartbeat
                hb = get_heartbeat()
                if not hb.enabled:
                    return
                pool = self.alloc.stats()
                fields = dict(
                    decode_slot_util=round(self.slot_util, 4),
                    kv_pool_used_frac=pool['used_frac'],
                    kv_pool_high_water_frac=pool['high_water_frac'],
                    kv_pool_failed_allocs=pool['failed_allocs'])
                # fraction of decode-ready slot-steps lost to prefill
                # head-of-line blocking (engine lifetime; the live
                # "prefill stalls decode" gauge)
                denom = self.stall_slot_steps + self.occupancy_sum
                if denom:
                    fields['decode_stall_frac'] = round(
                        self.stall_slot_steps / denom, 4)
                cm = self._costmodel
                if cm is not None and self.device_seconds > 0:
                    cost = cm.engine_cost(
                        prefill_tokens=self.prefill_tokens,
                        decode_tokens=self.decode_tokens,
                        prefill_steps=self.prefill_steps,
                        decode_steps=self.decode_steps,
                        slots=self.slots,
                        table_positions=self.max_pages * self.page_size,
                        kv_positions=self.kv_positions,
                        attn_positions=self.attn_positions,
                        kv_read_path=self.kv_read_path,
                        page_read_positions=self.page_read_positions)
                    mfu = cm.mfu(cost.flops, self.device_seconds)
                    mbu = cm.mbu(cost.bytes_total, self.device_seconds)
                    if mfu is not None:
                        fields['mfu'] = round(mfu, 6)
                    if mbu is not None:
                        fields['mbu'] = round(mbu, 6)
                hb.note(**fields)
            except Exception:
                pass

    def warm(self) -> int:
        """Pre-compile the engine's step with an all-inactive dummy
        dispatch — writes land on the garbage page, the pool is
        otherwise untouched.  The mixed engine compiles ONE shape (both
        cond-gated sub-batches live in the same executable); the legacy
        ``mixed_step=False`` engine compiles two.  Returns the number
        of shapes compiled (0 when already hot)."""
        model = self.model
        warmed = 0
        zs = jnp.zeros((self.slots,), jnp.int32)
        if self.spec:
            return self._warm_spec()
        if self.mixed:
            kind, shape = 'mixed', (self.slots, self.page_size + 1)
            if not model._first_dispatch(kind, shape,
                                         self.temperature, self.top_k):
                return 0
            cs0 = model.perf.compile_seconds
            args = (model.params, self.pool,
                    jnp.zeros((self.slots, self.page_size), jnp.int32),
                    zs, zs, jnp.zeros((self.slots, 1), jnp.int32),
                    zs, zs, jnp.asarray(self.table.table),
                    self._base_rng)
            with use_mesh(model.mesh), device_call(model.perf,
                                                   first=True):
                nxt, self.pool = self._step_fn(*args)
                jax.block_until_ready(nxt)
            model._note_compile(kind, shape,
                                model.perf.compile_seconds - cs0,
                                fn=self._step_fn,
                                args=(model.params, self.pool)
                                + args[2:],
                                extra={'attn_width':
                                       self.max_pages * self.page_size,
                                       'kv_read_path':
                                       self.kv_read_path})
            return 1
        for t in (self.page_size, 1):
            kind = 'prefill_chunk' if t > 1 else 'decode'
            if not model._first_dispatch(kind, (self.slots, t),
                                         self.temperature, self.top_k):
                continue
            cs0 = model.perf.compile_seconds
            with use_mesh(model.mesh), device_call(model.perf,
                                                   first=True):
                nxt, self.pool = self._step_fn(
                    model.params, self.pool,
                    jnp.zeros((self.slots, t), jnp.int32),
                    zs, zs, jnp.asarray(self.table.table),
                    self._base_rng)
                jax.block_until_ready(nxt)
            model._note_compile(kind, (self.slots, t),
                                model.perf.compile_seconds - cs0,
                                fn=self._step_fn,
                                args=(model.params, self.pool,
                                      np.zeros((self.slots, t), np.int32),
                                      np.zeros((self.slots,), np.int32),
                                      np.zeros((self.slots,), np.int32),
                                      np.asarray(self.table.table),
                                      self._base_rng),
                                extra={'attn_width':
                                       self.max_pages * self.page_size,
                                       'kv_read_path':
                                       self.kv_read_path})
            warmed += 1
        return warmed

    def _warm_spec(self) -> int:
        """Pre-compile the speculative engine's two executables (draft
        propose + target verify) with all-inactive dummy dispatches."""
        model = self.model
        K = self.spec_k
        warmed = 0
        zs = jnp.zeros((self.slots,), jnp.int32)
        pt = jnp.asarray(self.table.table)
        pf0 = jnp.zeros((self.slots, self.page_size), jnp.int32)
        d_kind, d_shape = 'spec_draft', (self.slots, self.page_size + K)
        if model._first_dispatch(d_kind, d_shape, self.temperature,
                                 self.top_k):
            cs0 = model.perf.compile_seconds
            with device_call(model.perf, first=True):
                props, self.draft_pool = self._draft_step_fn(
                    self.draft.params, self.draft_pool, pf0, zs, zs,
                    zs, zs, zs, pt)
                jax.block_until_ready(props)
            model._note_compile(d_kind, d_shape,
                                model.perf.compile_seconds - cs0)
            warmed += 1
        v_kind = 'spec_mixed'
        v_shape = (self.slots, self.page_size + K + 1)
        if model._first_dispatch(v_kind, v_shape, self.temperature,
                                 self.top_k):
            cs0 = model.perf.compile_seconds
            with use_mesh(model.mesh), device_call(model.perf,
                                                   first=True):
                pf_nxt, vf_out, self.pool = self._spec_step_fn(
                    model.params, self.pool, pf0, zs, zs,
                    jnp.zeros((self.slots, K + 1), jnp.int32),
                    zs, zs, pt, self._base_rng)
                jax.block_until_ready(vf_out)
            model._note_compile(
                v_kind, v_shape, model.perf.compile_seconds - cs0,
                extra={'attn_width': self.max_pages * self.page_size,
                       'kv_read_path': self.kv_read_path})
            warmed += 1
        return warmed

    @property
    def slot_util(self) -> float:
        """Mean fraction of decode-step slots occupied by live rows."""
        if not self.decode_steps:
            return 0.0
        return self.occupancy_sum / (self.decode_steps * self.slots)

    def snapshot(self) -> Dict:
        """Counter snapshot for per-drain deltas (``stats(since=...)``)."""
        with self._lock:
            return {'steps': self.steps,
                    'prefill_steps': self.prefill_steps,
                    'decode_steps': self.decode_steps,
                    'occupancy_sum': self.occupancy_sum,
                    'joined': self.joined,
                    'prio_joined': self.prio_joined,
                    'retired': self.retired,
                    'cancelled_rows': self.cancelled_rows,
                    'device_seconds': self.device_seconds,
                    'prefill_tokens': self.prefill_tokens,
                    'kv_positions': self.kv_positions,
                    'attn_positions': self.attn_positions,
                    'page_read_positions': self.page_read_positions,
                    'stall_slot_steps': self.stall_slot_steps,
                    'decode_tokens': self.decode_tokens,
                    'prefix_hits': self.prefix_hits,
                    'prefix_saved_tokens': self.prefix_saved_tokens,
                    'prefix_saved_attn': self.prefix_saved_attn,
                    'prefix_cow_copies': self.prefix_cow_copies,
                    'spec_proposed': self.spec_proposed,
                    'spec_accepted': self.spec_accepted}

    def stats(self, since: Optional[Dict] = None) -> Dict:
        """Engine counters — lifetime by default, or the delta since a
        :meth:`snapshot` (what one drained call did; the flight
        recorder's per-drain ``engine`` records use this so a resident
        engine's Nth task never re-reports task N-1's steps)."""
        base = since or {}
        with self._lock:
            from opencompass_tpu.obs.reqtrace import percentile
            from opencompass_tpu.obs.timeline import _downsample
            d_decode = self.decode_steps - base.get('decode_steps', 0)
            d_occ = self.occupancy_sum - base.get('occupancy_sum', 0)
            d_steps = self.steps - base.get('steps', 0)
            series = [float(v) for v in self._occ_series]
            step_recs = list(self._step_records)
            if since is not None:
                # the bounded series keeps only the recent tail; the
                # delta's decode steps are its newest entries
                series = series[max(0, len(series) - d_decode):]
                step_recs = step_recs[max(0,
                                          len(step_recs) - d_steps):]
            walls = [r['w'] for r in step_recs]
            # per-step detail capped for the timeline record: stride-
            # sample past 128 entries (aggregates stay exact; the
            # detail is the shape of the drain, not its totals)
            if len(step_recs) > 128:
                stride = (len(step_recs) + 127) // 128
                step_recs = step_recs[::stride]
            return {
                'slots': self.slots,
                'page_size': self.page_size,
                'pool_pages': self.num_pages,
                'steps': self.steps - base.get('steps', 0),
                'prefill_steps': self.prefill_steps
                - base.get('prefill_steps', 0),
                'decode_steps': d_decode,
                'joined': self.joined - base.get('joined', 0),
                'prio_joined': self.prio_joined
                - base.get('prio_joined', 0),
                'retired': self.retired - base.get('retired', 0),
                'cancelled_rows': self.cancelled_rows
                - base.get('cancelled_rows', 0),
                'slot_util': round(
                    d_occ / (d_decode * self.slots), 4) if d_decode
                else 0.0,
                'occupancy_series': [
                    round(v, 2) for v in _downsample(series)],
                # roofline inputs (obs/costmodel.engine_cost): device
                # wall, exact token/attended-position counts (decode
                # tokens processed = occupancy delta), and the gather
                # path's per-step table width
                'device_seconds': round(
                    self.device_seconds
                    - base.get('device_seconds', 0.0), 6),
                'prefill_tokens': self.prefill_tokens
                - base.get('prefill_tokens', 0),
                'decode_tokens': self.decode_tokens
                - base.get('decode_tokens', 0),
                'kv_positions': self.kv_positions
                - base.get('kv_positions', 0),
                'attn_positions': self.attn_positions
                - base.get('attn_positions', 0),
                'page_read_positions': self.page_read_positions
                - base.get('page_read_positions', 0),
                'table_positions': self.max_pages * self.page_size,
                'kv_read_path': self.kv_read_path,
                'mixed_step': self.mixed,
                'kv_pool': self.alloc.stats(),
                # per-step telemetry: the slot-composition records
                # (prefill vs decode vs stalled rows per step), the
                # stalled-slot-step total, and the step-wall spread —
                # what makes "prefill stalls decode slots" a measured
                # number instead of an assertion
                'stall_slot_steps': self.stall_slot_steps
                - base.get('stall_slot_steps', 0),
                # prefix-cache / speculative-decoding deltas (0/None
                # when the features are off — consumers treat absence
                # of savings and absence of the feature alike)
                'prefix_cache_enabled': self.prefix is not None,
                'prefix_hits': self.prefix_hits
                - base.get('prefix_hits', 0),
                'prefill_tokens_saved': self.prefix_saved_tokens
                - base.get('prefix_saved_tokens', 0),
                'prefix_saved_attn': self.prefix_saved_attn
                - base.get('prefix_saved_attn', 0),
                'prefix_cow_copies': self.prefix_cow_copies
                - base.get('prefix_cow_copies', 0),
                'prefix_cache': (self.prefix.stats()
                                 if self.prefix is not None else None),
                'speculative': self.spec,
                'spec_k': self.spec_k,
                'spec_proposed': self.spec_proposed
                - base.get('spec_proposed', 0),
                'spec_accepted': self.spec_accepted
                - base.get('spec_accepted', 0),
                'spec_accept_rate': round(
                    (self.spec_accepted - base.get('spec_accepted', 0))
                    / (self.spec_proposed
                       - base.get('spec_proposed', 0)), 4)
                if self.spec_proposed - base.get('spec_proposed', 0)
                else None,
                'steps_detail': step_recs,
                'step_wall_p50_ms': round(
                    percentile(walls, 0.50) * 1e3, 3)
                if walls else None,
                'step_wall_p99_ms': round(
                    percentile(walls, 0.99) * 1e3, 3)
                if walls else None,
            }

    def cost_fields(self, stats: Dict) -> Dict:
        """Roofline fields (flops / bytes_w / bytes_kv /
        bytes_kv_ideal / mfu / mbu) for one drain's :meth:`stats`
        delta; {} when the model has no transformer geometry.  Never
        raises — cost attribution is telemetry."""
        try:
            cm = self._costmodel
            if cm is None:
                return {}
            cost = cm.engine_cost(
                prefill_tokens=stats.get('prefill_tokens') or 0,
                decode_tokens=stats.get('decode_tokens') or 0,
                prefill_steps=stats.get('prefill_steps') or 0,
                decode_steps=stats.get('decode_steps') or 0,
                slots=stats.get('slots') or self.slots,
                table_positions=stats.get('table_positions')
                or self.max_pages * self.page_size,
                kv_positions=stats.get('kv_positions'),
                attn_positions=stats.get('attn_positions'),
                kv_read_path=stats.get('kv_read_path',
                                       self.kv_read_path),
                page_read_positions=stats.get('page_read_positions'))
            out = cm.fields(cost, stats.get('device_seconds'))
            saved_tokens = stats.get('prefill_tokens_saved') or 0
            if saved_tokens:
                # prefill FLOPs the radix prefix cache avoided (the
                # matmul + attention work of the skipped prompt tokens)
                out['flops_prefill_saved'] = int(cm.prefill_saved(
                    saved_tokens, stats.get('prefix_saved_attn') or 0))
            return out
        except Exception:
            return {}

    def profile_fields(self) -> Dict:
        """Gather-share of decode step wall for this engine's drains
        (obs/devprof.py): the sampled-trace measurement when
        ``--profile-steps`` captured any, else the memory-bound
        analytic share — labelled by ``gather_share_source`` so the
        report can tell them apart.  Never raises."""
        try:
            out: Dict = {}
            measured = None
            if _devprof is not None:
                out.update(_devprof.get_step_profiler().fields())
                measured = out.get('gather_share_measured')
                cm = self._costmodel
                if cm is not None:
                    out['gather_share_modeled'] = \
                        _devprof.modeled_gather_share(
                            cm, self.slots,
                            self.max_pages * self.page_size,
                            kv_read_path=self.kv_read_path)
            share = measured if measured \
                else out.get('gather_share_modeled')
            if share:
                out['gather_share'] = share
                out['gather_share_source'] = \
                    'measured' if measured else 'modeled'
            return out
        except Exception:
            return {}

    # -- draining ----------------------------------------------------------

    def drain(self, rows: List[_EngineRow],
              on_result: Optional[Callable[[_EngineRow], None]] = None,
              timeout: Optional[float] = None):
        """Drive the engine until every row in ``rows`` retires,
        delivering each (in retirement order) through ``on_result``.
        Safe to call from several threads at once: the driver lock picks
        one stepper, everyone else waits on their rows' events — which
        is exactly how an interactive request rides a sweep's resident
        step."""
        deadline = time.monotonic() + timeout if timeout else None
        pending = {id(r): r for r in rows}
        delivered: set = set()

        def flush():
            ready = sorted((r for r in pending.values()
                            if r.event.is_set()
                            and id(r) not in delivered),
                           key=lambda r: r.retire_seq)
            for row in ready:
                delivered.add(id(row))
                if on_result is not None:
                    on_result(row)
            for row in ready:
                del pending[id(row)]

        while True:
            flush()
            if not pending:
                return
            if deadline is not None and time.monotonic() >= deadline:
                raise TimeoutError(
                    f'{len(pending)} row(s) still in flight after '
                    f'{timeout:.0f}s')
            if self._driver.acquire(blocking=False):
                try:
                    progressed = self._device_step()
                finally:
                    self._driver.release()
                if not progressed and any(not r.event.is_set()
                                          for r in pending.values()):
                    raise RuntimeError(
                        'continuous engine stalled with rows pending '
                        '(page pool misconfigured?)')
            else:
                next(iter(pending.values())).event.wait(0.05)


@MODELS.register_module()
class JaxLM(BaseModel):
    """A causal LM evaluated through jitted JAX functions.

    Args:
        path: HF checkpoint dir (config.json + shards) or '' for random
            init from ``config`` (hermetic tests / benchmarks).
        config: TransformerConfig, preset name ('llama','opt',...) or dict
            of TransformerConfig fields; required when ``path`` has no
            config.json.
        parallel: mesh axis sizes, e.g. ``dict(data=-1, model=1, seq=1)``.
            Only built when >1 device is visible or sizes demand it.
        dtype: parameter/compute dtype override ('bfloat16' on TPU,
            'float32' for bit-stable CPU tests).
        batch_bucket / seq_bucket_min: shape-bucketing knobs.
    """

    # inferencers may re-pack/reorder batches (length-aware planner,
    # icl/inferencers/schedule.py): per-row outputs are batch-independent
    # here, and fewer distinct (B, S) buckets means fewer XLA compiles
    supports_batch_plan = True
    # opt-in for the continuous-batching decode engine (slot scheduler
    # over a paged KV cache): config-selectable via ``continuous_batching``
    # — the gen inferencer's planner degenerates to a feed queue and rows
    # retire individually instead of per fixed-shape batch
    supports_continuous_batching = True

    def __init__(self,
                 path: str = '',
                 max_seq_len: int = 2048,
                 config: Union[TransformerConfig, str, Dict, None] = None,
                 parallel: Optional[Dict] = None,
                 dtype: Optional[str] = None,
                 tokenizer_path: Optional[str] = None,
                 tokenizer_kwargs: Optional[Dict] = None,
                 meta_template: Optional[Dict] = None,
                 generation_kwargs: Optional[Dict] = None,
                 seed: int = 0,
                 tokenizer_only: bool = False,
                 batch_padding: bool = True,
                 quantize: Optional[str] = None,
                 convert_cache: Optional[str] = None,
                 shared_prefix: bool = True,
                 continuous_batching: bool = False,
                 decode_slots: int = 8,
                 kv_page_size: int = 64,
                 kv_pool_pages: Optional[int] = None,
                 mixed_step: bool = True,
                 ragged_kernel: str = 'auto',
                 prefix_cache: bool = False,
                 draft_model: Optional[Dict] = None,
                 draft_k: int = 4,
                 run_cfg: Optional[Dict] = None):
        super().__init__(path=path, max_seq_len=max_seq_len,
                         tokenizer_only=tokenizer_only,
                         meta_template=meta_template,
                         generation_kwargs=generation_kwargs)
        try:
            self.cfg = self._resolve_config(path, config, dtype, max_seq_len)
        except ValueError:
            if not tokenizer_only:
                raise
            self.cfg = None  # token counting needs no model config
        # NOTE: with no local checkpoint/tokenizer this falls back to the
        # deterministic byte tokenizer (512-id floor).  Byte token counts
        # differ from the real tokenizer's (usually ~3-4x more tokens per
        # text), so in tokenizer_only mode the SizePartitioner's cost
        # model sees inflated-but-consistent sizes: task packing stays
        # balanced, absolute size estimates don't transfer to real-vocab
        # runs.
        self.tokenizer = load_tokenizer(
            tokenizer_path or path, tokenizer_kwargs,
            vocab_size=self.cfg.vocab_size if self.cfg else 512)
        if self.eos_token_id is None:
            self.eos_token_id = self.tokenizer.eos_token_id
        # token-id LRU shared by get_token_len and _encode_batch so the
        # truncation loop's counting pass tokenizes each prompt once.
        # Both caches key on a string digest and are bounded: full prompt
        # strings or unbounded growth would pile up GBs over a 100k-sample
        # task (prompts can be KBs each, shrink loops multiply variants).
        self._token_len_cache: 'OrderedDict[bytes, int]' = OrderedDict()
        self._token_ids_cache: 'OrderedDict[bytes, List[int]]' = \
            OrderedDict()
        self._ids_cache_max = 8192
        self._len_cache_max = 1_000_000
        # persisted token-length cache (utils/toklen_cache.py): when the
        # sweep pins a cache root, resumed/retried/sibling tasks start
        # from the lengths a previous process already measured instead
        # of re-tokenizing the dataset.  Text never hits disk — only
        # the 16-byte digests this cache is keyed on.
        from opencompass_tpu.utils import toklen_cache
        self._toklen_dir = toklen_cache.resolve_dir()
        self._toklen_digest = toklen_cache.tokenizer_digest(
            self.tokenizer, tokenizer_path or path)
        if self._toklen_dir:
            self._token_len_cache.update(
                toklen_cache.load(self._toklen_dir, self._toklen_digest))
        self._gen_fn_cache: Dict[tuple, object] = {}
        # (kernel kind, static args, shape bucket) keys already dispatched:
        # an unseen key means jax.jit compiles on this call, so its
        # duration is attributed to PerfCounters.compile_seconds (the obs
        # trace report's first-call-vs-steady device_call split)
        self._dispatched_keys: set = set()
        # shared-prefix prefill reuse: a batch whose prompts share a long
        # common token prefix (fixed few-shot ICE blocks; PPL label
        # variants) prefills it once (nn: forward_shared for scoring,
        # prefill_suffix for generation).  Applied when the batch's
        # common prefix is >= _sp_quantum tokens; the prefix length is
        # rounded DOWN to a multiple of the quantum so jit shape buckets
        # stay bounded.  The quantum is coarse (256) on purpose: every
        # distinct (prefix, suffix) shape pair compiles its own
        # executables, and occasional shape pairs hit pathologically
        # slow XLA compiles (measured 10-16 min through the remote-
        # compile tunnel at 7B) — fewer pairs, fewer rolls of that die.
        # Off for prefix-LM / ALiBi models and seq/model meshes.
        self.shared_prefix = shared_prefix
        self._sp_quantum = 256
        # quantize modes compose 'base[-kvN]': base 'int8' (weight-only),
        # 'w8a8' (int8 weights + dynamic per-token int8 activations on
        # the MXU), or 'w4a8' (int4 weights packed two-per-uint8 with
        # 128-wide group scales, unpacked inside the jit — nn/quant.py
        # int4x2 — + int8 activations); '-kv'/'-kv8' adds an int8 decode
        # KV cache, '-kv4' an int4 one.  'w8a8-kv8' is the accuracy-
        # pinned serving recipe (int8 KV rides the Pallas decode kernel
        # on TPU); 'w8a8-kv4'/'w4a8-kv4' halve the cache/decode weight
        # stream again (group-RTN int4: check the agreement probe for
        # your model before trusting scores).
        base, dash, kv = (quantize or '').partition('-')
        if quantize is not None and (
                base not in ('int8', 'w8a8', 'w4a8') or
                (dash and kv not in ('kv', 'kv8', 'kv4'))):
            raise ValueError(f'unsupported quantize={quantize!r} '
                             "(want 'int8'|'w8a8'|'w4a8' optionally + "
                             "'-kv8'|'-kv4', e.g. 'w8a8-kv4')")
        self.quantize = quantize
        self._weight_mode = 'int4x2' if base == 'w4a8' else 'int8'
        if base == 'w4a8' and abs((parallel or {}).get('model', 1)) != 1:
            raise NotImplementedError(
                'w4a8 packed weights are stored NT and do not yet carry '
                'tensor-parallel sharding specs; use model=1 or w8a8')
        if quantize and self.cfg is not None:
            import dataclasses
            updates = {}
            if kv:
                updates['kv_quant'] = 'int4' if kv == 'kv4' else 'int8'
            if base in ('w8a8', 'w4a8'):
                updates['act_quant'] = True
            if updates:
                self.cfg = dataclasses.replace(self.cfg, **updates)
        self.convert_cache = convert_cache
        # continuous-batching decode engine (slot scheduler over a paged
        # KV cache): built lazily on first generate_continuous; the
        # dense lax.while_loop path stays the fallback (and the only
        # path for beam search / ALiBi / prefix-LM / meshes)
        self.continuous_batching = bool(continuous_batching)
        self.decode_slots = int(decode_slots)
        self.kv_page_size = int(kv_page_size)
        self.kv_pool_pages = kv_pool_pages
        # one mixed prefill+decode engine step (single compiled shape;
        # prefilling rows no longer stall decode-ready slots).  False
        # keeps the legacy two-shape step — the stall-regression pin in
        # tests/test_continuous_batching.py measures the difference.
        self.continuous_mixed_step = bool(mixed_step)
        # KV-read path inside the engine step: 'auto' takes the Pallas
        # ragged-paged-attention kernel on a real TPU where
        # nn/transformer.ragged_kernel_active covers the config and the
        # XLA gather everywhere else; 'on' forces the kernel (interpret
        # mode off-TPU — correct but slow, for tests/bench); 'off'
        # pins the gather.
        if ragged_kernel not in ('auto', 'on', 'off'):
            raise ValueError(f'unsupported ragged_kernel='
                             f'{ragged_kernel!r} (want auto|on|off)')
        self.ragged_kernel = ragged_kernel
        # radix prefix cache over the engine's page pool: rows whose
        # prompts share a cached prefix map its pages read-only and
        # prefill only their suffix (nn/paged_kv.RadixPrefixCache).
        # Off by default: the trie deliberately HOLDS pages between
        # drains (that is the point — a later task reuses them), which
        # changes the pool-empty-after-drain invariant some telemetry
        # consumers assume.
        self.prefix_cache = bool(prefix_cache)
        # draft-model speculative decoding: a small JaxLM built from
        # this config dict (e.g. dict(config='tiny', seed=0)) proposes
        # draft_k greedy tokens per engine step; the target verifies
        # them in one fused call.  Greedy-only — see speculative_eligible
        self.draft_model = draft_model
        self.draft_k = int(draft_k)
        self._draft_lm: Optional['JaxLM'] = None
        self._cont_engine: Optional[ContinuousEngine] = None
        self._cont_engine_key = None
        # worker protocol thread + sweep thread can both reach for the
        # engine; double-building would allocate the page pool twice
        self._cont_engine_lock = threading.Lock()
        self.mesh = None
        self.params = None
        if not tokenizer_only:
            self._load_params(path, seed)
            self._maybe_shard(parallel)

    # -- setup -------------------------------------------------------------

    def _resolve_config(self, path, config, dtype, max_seq_len
                        ) -> Optional[TransformerConfig]:
        import dataclasses
        if isinstance(config, TransformerConfig):
            cfg = config
        elif isinstance(config, str):
            cfg = getattr(TransformerConfig, config)()
        elif isinstance(config, dict):
            kw = dict(config)
            preset = kw.pop('preset', None)
            if preset:
                # call the preset with the overrides (NOT replace() on a
                # built default) so derived fields — head_dim,
                # num_kv_heads, intermediate_size — are recomputed from
                # the overridden sizes
                cfg = getattr(TransformerConfig, preset)(**kw)
            else:
                cfg = TransformerConfig(**kw)
        elif path and os.path.isfile(os.path.join(path, 'config.json')):
            from opencompass_tpu.nn.hf_convert import load_hf_config
            cfg = TransformerConfig.from_hf_config(load_hf_config(path))
        else:
            raise ValueError('JaxLM needs `config` or a checkpoint path '
                             'with config.json')
        if dtype:
            cfg = dataclasses.replace(cfg, dtype=dtype)
        if cfg.max_seq_len < max_seq_len:
            cfg = dataclasses.replace(cfg, max_seq_len=max_seq_len)
        return cfg

    def _load_params(self, path: str, seed: int):
        from opencompass_tpu.nn.sat_convert import is_sat_checkpoint
        if is_sat_checkpoint(path):
            # GLM-130B-style SAT model-parallel shards (nn/sat_convert.py)
            from opencompass_tpu.nn.sat_convert import \
                convert_sat_checkpoint_cached
            self.cfg, self.params = convert_sat_checkpoint_cached(
                path, self.cfg, cache_dir=self.convert_cache)
            logger.info(f'loaded SAT checkpoint from {path}')
            if self.quantize:
                from opencompass_tpu.nn.quant import quantize_params
                self.params = quantize_params(self.params, self.cfg,
                                              mode=self._weight_mode)
            return
        has_ckpt = path and os.path.isdir(path) and any(
            f.endswith(('.safetensors', '.bin')) for f in os.listdir(path))
        if has_ckpt:
            from opencompass_tpu.nn.hf_convert import \
                convert_checkpoint_cached
            # stays host numpy: _maybe_shard places shards directly, so the
            # full model never has to fit on a single chip
            self.cfg, self.params = convert_checkpoint_cached(
                path, self.cfg, cache_dir=self.convert_cache)
            logger.info(f'loaded checkpoint from {path}')
            if self.quantize:
                # host-side: only the int8 tensors ever reach a chip
                from opencompass_tpu.nn.quant import quantize_params
                self.params = quantize_params(self.params, self.cfg,
                                              mode=self._weight_mode)
        elif jax.process_count() > 1:
            if path:
                logger.warning(f'no weights under {path!r}; random init '
                               f'(seed={seed})')
            # host-side init: every process derives the identical pytree
            # from the seed, then contributes its local shards.  (Must be a
            # *local* device — jax.devices()[0] may belong to rank 0.)
            with jax.default_device(jax.local_devices(backend='cpu')[0]):
                self.params = init_params(self.cfg, jax.random.PRNGKey(seed))
            if self.quantize:
                from opencompass_tpu.nn.quant import quantize_params
                self.params = jax.tree_util.tree_map(np.asarray,
                                                     self.params)
                self.params = quantize_params(self.params, self.cfg,
                                              mode=self._weight_mode)
        else:
            if path:
                logger.warning(f'no weights under {path!r}; random init '
                               f'(seed={seed})')
            if self.quantize and self._weight_mode == 'int4x2':
                # direct packed init: the fused init+quantize below needs
                # the full bf16 stack as the pack's input, which exceeds
                # HBM for the geometries w4a8 exists to serve (13B-class
                # on one 16 GB chip) — see nn/quant.init_packed_params
                from opencompass_tpu.nn.quant import init_packed_params
                cfg = self.cfg
                # oct-lint: disable=OCT007(one-shot fused init program per model build; the wrapper is intentionally discarded)
                self.params = jax.jit(
                    lambda key: init_packed_params(cfg, key))(
                        jax.random.PRNGKey(seed))
            elif self.quantize:
                # ONE fused program: the bf16 weights are scheduler temps
                # freed as each int8 consumer runs, so init+quantize of a
                # near-HBM-sized model fits without fragmentation (a
                # sequence of per-leaf donations fragments the allocator;
                # host init is minutes-slow at 7B)
                from opencompass_tpu.nn.quant import quantize_params
                cfg = self.cfg
                mode = self._weight_mode
                # oct-lint: disable=OCT007(one-shot fused init+quantize program per model build; the wrapper is intentionally discarded)
                self.params = jax.jit(
                    lambda key: quantize_params(init_params(cfg, key),
                                                cfg, mode=mode))(
                                                    jax.random.PRNGKey(seed))
            else:
                self.params = init_params(self.cfg,
                                          jax.random.PRNGKey(seed))

    def _maybe_shard(self, parallel: Optional[Dict]):
        n_dev = len(jax.devices())
        parallel = parallel or {}
        want = max(1, abs(parallel.get('model', 1)) *
                   abs(parallel.get('seq', 1)))
        if n_dev == 1 and want <= 1:
            # no mesh: commit host (checkpoint) params to the device once,
            # rather than re-uploading per jitted call
            self.params = jax.tree_util.tree_map(jnp.asarray, self.params)
            return
        if parallel.get('seq', 1) > 1 and self.cfg is not None \
                and self.cfg.positional == 'alibi':
            raise ValueError('ring attention (seq>1) does not support '
                             'ALiBi models yet; use data/model axes')
        spec = MeshSpec(data=parallel.get('data', -1),
                        model=parallel.get('model', 1),
                        seq=parallel.get('seq', 1))
        self.mesh = make_mesh(spec)
        self.params = shard_params(self.params, self.cfg, self.mesh)
        shape = dict(zip(self.mesh.axis_names, self.mesh.devices.shape))
        logger.info(f'mesh: {shape}')

    # -- multi-host array plumbing -----------------------------------------

    def _multihost(self) -> bool:
        return self.mesh is not None and jax.process_count() > 1

    def _put(self, arr, spec: P):
        """Host array -> device array.  Across hosts every process holds the
        same full batch; each contributes the shards its devices own."""
        if not self._multihost():
            return jnp.asarray(arr)
        from opencompass_tpu.parallel.distributed import make_global_array
        return make_global_array(arr, NamedSharding(self.mesh, spec))

    def _replicate(self, x):
        """Inside-jit constraint making an output fully replicated, so every
        host can read it without cross-process gathers afterwards."""
        if not self._multihost():
            return x
        return jax.lax.with_sharding_constraint(
            x, NamedSharding(self.mesh, P()))

    # -- jitted kernels (cached per static config) -------------------------

    @functools.cached_property
    def _ppl_fn(self):
        cfg = self.cfg
        mesh = self.mesh
        use_ring = mesh is not None and mesh.shape.get('seq', 1) > 1
        if use_ring:
            if cfg.prefix_lm:
                raise ValueError('prefix-LM scoring is not supported with '
                                 'sequence parallelism (ring attention is '
                                 'causal-blocked); use a data/model mesh')
            from opencompass_tpu.parallel.ring_attention import ring_forward

            @jax.jit
            def ppl(params, tokens, mask, mask_length):
                logits = ring_forward(params, cfg, tokens, mask, mesh)
                return self._replicate(
                    sequence_nll(logits, tokens, mask, mask_length))
            return ppl

        @jax.jit
        def ppl(params, tokens, mask, mask_length):
            prefix_mask = None
            if cfg.prefix_lm:
                # scoring batches are right-padded, so the first
                # mask_length[i] slots are the bidirectional context
                pos = jnp.arange(tokens.shape[1])[None, :]
                prefix_mask = pos < mask_length[:, None]
            logits = forward(params, cfg, tokens, mask,
                             prefix_mask=prefix_mask)
            return self._replicate(
                sequence_nll(logits, tokens, mask, mask_length))
        return ppl

    def _gen_fn(self, max_new: int, temperature: float, top_k: int,
                num_beams: int = 1, length_penalty: float = 1.0,
                prefixed: bool = False):
        # per-instance cache (a class-level lru_cache would pin `self` — and
        # its multi-GB param pytree — alive across model swaps)
        key = (max_new, temperature, top_k, num_beams, length_penalty,
               prefixed)
        fn = self._gen_fn_cache.get(key)
        if fn is not None:
            return fn
        cfg = self.cfg
        eos = self.eos_token_id
        pad = self.tokenizer.pad_token_id or 0

        if prefixed:
            @jax.jit
            def gen(params, prefix, tokens, mask, rng):
                out = greedy_generate_prefixed(
                    params, cfg, prefix, tokens, mask, max_new,
                    eos_token_id=eos, pad_token_id=pad,
                    temperature=temperature, top_k=top_k, rng=rng)
                return jax.tree_util.tree_map(self._replicate, out)
            self._gen_fn_cache[key] = gen
            return gen

        @jax.jit
        def gen(params, tokens, mask, rng):
            if num_beams > 1:
                # beam search is deterministic: rng unused (reference
                # glm.py:166-285 BeamSearchStrategy semantics)
                out = beam_generate(params, cfg, tokens, mask, max_new,
                                    num_beams=num_beams,
                                    eos_token_id=eos, pad_token_id=pad,
                                    length_penalty=length_penalty)
            else:
                out = greedy_generate(params, cfg, tokens, mask, max_new,
                                      eos_token_id=eos, pad_token_id=pad,
                                      temperature=temperature,
                                      top_k=top_k, rng=rng)
            return jax.tree_util.tree_map(self._replicate, out)
        self._gen_fn_cache[key] = gen
        return gen

    def _first_dispatch(self, kind: str, *key_parts) -> bool:
        """True the first time a (kind, static-arg, shape-bucket) key is
        dispatched — the call that pays XLA compilation."""
        key = (kind,) + key_parts
        if key in self._dispatched_keys:
            return False
        self._dispatched_keys.add(key)
        return True

    @functools.cached_property
    def shape_signature(self) -> Optional[str]:
        """Model identity for the compile-cache shape manifest: configs
        producing the same signature compile the same executables for a
        given (kind, B, S), so `cli plan --cache-dir` can join planned
        shapes against shapes a previous run already compiled."""
        if self.cfg is None:
            return None
        import dataclasses
        ident = (dataclasses.asdict(self.cfg), self.quantize,
                 self.max_seq_len)
        return hashlib.blake2b(repr(ident).encode('utf-8'),
                               digest_size=8).hexdigest()

    def _note_compile(self, kind: str, shape, seconds: float,
                      fn=None, args=None, extra=None):
        """Record a first-dispatched shape bucket (and its observed
        first-call seconds) into the persistent cache's sidecar shape
        manifest, and — when tracing is on — into the compile audit
        (``{obs_dir}/compiles.jsonl``, obs/compileaudit.py).  ``fn`` /
        ``args`` let the audit re-lower the just-compiled executable
        (cache-served, ~ms) and read XLA's own cost/memory accounting;
        ``extra['attn_width']`` carries the paged table width the
        analytic reconciliation needs.  Never raises."""
        try:
            from opencompass_tpu.utils import compile_cache
            sig = self.shape_signature
            if sig:
                compile_cache.record_shape(sig, kind, shape, seconds)
        except Exception:
            pass
        try:
            from opencompass_tpu.obs import compileaudit
            compileaudit.get_compileaudit().record_compile(
                kind, shape, seconds, fn=fn, args=args, model=self,
                extra=extra)
        except Exception:
            pass

    def _gen_params(self) -> tuple:
        """(temperature, top_k, seed, num_beams, length_penalty) resolved
        from ``generation_kwargs`` — the static half of the gen-fn cache
        key, shared by :meth:`generate_async` and :meth:`warm_up` so a
        warmed shape is exactly the shape the run dispatches."""
        gk = dict(self.generation_kwargs)
        if gk.get('do_sample', False):
            temperature = float(gk.get('temperature', 1.0))  # HF default
        else:
            temperature = 0.0  # greedy
        return (temperature, int(gk.get('top_k', 0)),
                int(gk.get('seed', 0)), int(gk.get('num_beams', 1)),
                float(gk.get('length_penalty', 1.0)))

    def warm_up(self, specs: List[Dict]) -> int:
        """Pre-compile the planned (B, S_bucket) set before the first
        real batch: each spec is ``{kind: 'ppl'|'gen'|'choice', b, s[,
        max_out_len]}`` (the planner's shape census).  Dispatches one
        dummy batch per unseen bucket through the same jitted functions
        and ``_first_dispatch`` keys the real calls use, so compile time
        lands in one visible warm-up span (and in the persistent cache)
        instead of stalling mid-run.  Shared-prefix variants are not
        warmed (their shapes depend on batch content); those still
        compile lazily.  Returns the number of buckets compiled."""
        if self.tokenizer_only or self.params is None:
            return 0
        pad = self.tokenizer.pad_token_id or 0
        temperature, top_k, seed, num_beams, length_penalty = \
            self._gen_params()
        warmed = 0
        with use_mesh(self.mesh):
            for spec in specs:
                try:
                    kind = spec['kind']
                    if kind == 'gen_continuous':
                        # continuous sweeps dispatch exactly the
                        # engine's two shapes — warm those, not the
                        # dense census
                        if self.continuous_active:
                            warmed += self.continuous_engine().warm()
                        continue
                    max_new = int(spec.get('max_out_len') or 0)
                    # gen batches pad under a decode-reserved cap
                    # (max_seq_len - max_out_len, matching
                    # generate_async); re-bucketing a census shape
                    # without it would round a clamped S back up and
                    # compile an executable the run never dispatches
                    max_len = max(self.max_seq_len - max_new, 32) \
                        if kind == 'gen' else None
                    B, S = self.plan_shape(int(spec['b']),
                                           int(spec['s']), max_len)
                    cs0 = self.perf.compile_seconds
                    spec_arrs = P('data', None)
                    aot = None
                    tokens = self._put(np.full((B, S), pad, np.int32),
                                       spec_arrs)
                    mask = self._put(np.ones((B, S), bool), spec_arrs)
                    if kind == 'ppl':
                        if not self._first_dispatch('ppl', False, (B, S)):
                            continue
                        mlb = self._put(np.zeros((B,), np.int32),
                                        P('data'))
                        with device_call(self.perf, first=True):
                            out = self._ppl_fn(self.params, tokens,
                                               mask, mlb)
                            jax.block_until_ready(out)
                        aot = (self._ppl_fn,
                               (self.params, tokens, mask, mlb))
                    elif kind == 'choice':
                        if not self._first_dispatch('choice', (B, S)):
                            continue
                        with device_call(self.perf, first=True):
                            out = self._choice_logits_fn(self.params,
                                                         tokens, mask)
                            jax.block_until_ready(out)
                        aot = (self._choice_logits_fn,
                               (self.params, tokens, mask))
                    elif kind == 'gen':
                        if not max_new:
                            # unknown decode length = unknown jit key; a
                            # guessed warm-up would compile a shape the
                            # run never dispatches (pure waste at 7B)
                            continue
                        if not self._first_dispatch(
                                'gen', False, (B, S), max_new,
                                temperature, top_k, num_beams,
                                length_penalty):
                            continue
                        fn = self._gen_fn(max_new, temperature, top_k,
                                          num_beams, length_penalty)
                        rng = self._put(jax.random.PRNGKey(seed), P())
                        with device_call(self.perf, first=True):
                            out = fn(self.params, tokens, mask, rng)
                            jax.block_until_ready(out)
                        aot = (fn, (self.params, tokens, mask, rng))
                    else:
                        continue
                    warmed += 1
                    aot_fn, aot_args = aot if aot else (None, None)
                    self._note_compile(kind, (B, S),
                                       self.perf.compile_seconds - cs0,
                                       fn=aot_fn, args=aot_args)
                except Exception as exc:
                    logger.warning(
                        f'warm-up of {spec} failed (will compile '
                        f'lazily): {exc}')
        return warmed

    def save_caches(self):
        """Persist the token-length cache for successor processes (the
        task layer calls this when a model's datasets finish)."""
        if self._toklen_dir and self._token_len_cache:
            from opencompass_tpu.utils import toklen_cache
            toklen_cache.save(self._toklen_dir, self._toklen_digest,
                              self._token_len_cache)

    # -- BaseModel contract ------------------------------------------------

    @staticmethod
    def _cache_key(text: str) -> bytes:
        return hashlib.blake2b(text.encode('utf-8'),
                               digest_size=16).digest()

    def _encode_ids(self, text: str) -> List[int]:
        """Tokenize with the tokenizer's own specials (BOS for llama-family
        HF tokenizers), matching the reference's HF-default tokenization
        (reference models/huggingface.py:142,181,262).  Cached: truncation
        loops re-count the same shrinking prompts (ADVICE r1)."""
        key = self._cache_key(text)
        ids = self._token_ids_cache.get(key)
        if ids is None:
            ids = self.tokenizer.encode(text, add_special_tokens=True)
            self._token_ids_cache[key] = ids
            if len(self._token_ids_cache) > self._ids_cache_max:
                self._token_ids_cache.popitem(last=False)
            self._token_len_cache[key] = len(ids)
            if len(self._token_len_cache) > self._len_cache_max:
                self._token_len_cache.popitem(last=False)
        else:
            self._token_ids_cache.move_to_end(key)
        return ids

    def get_token_len(self, prompt: str) -> int:
        prompt = str(prompt)
        n = self._token_len_cache.get(self._cache_key(prompt))
        if n is None:
            n = len(self._encode_ids(prompt))
        return n

    @staticmethod
    def _common_prefix_len(ids: List[List[int]]) -> int:
        """Longest common token prefix across the batch's id rows."""
        if len(ids) < 2:
            return 0
        n = len(ids[0])
        for row in ids[1:]:
            m = min(n, len(row))
            i = 0
            while i < m and row[i] == ids[0][i]:
                i += 1
            n = i
            if n == 0:
                break
        return n

    @property
    def shared_prefix_active(self) -> bool:
        """True when the shared-prefix machinery can structurally engage
        for this model (flag on, compatible config, no blocking mesh).
        Inferencers consult this before reshaping their batches around
        it — with it False, item-major PPL batching would shrink batches
        to len(labels) rows of plain forwards for no benefit."""
        mesh_ok = self.mesh is None or (
            not self._multihost()
            and self.mesh.shape.get('model', 1) == 1
            and self.mesh.shape.get('seq', 1) == 1)
        return bool(self.shared_prefix and mesh_ok
                    and self.cfg is not None and not self.cfg.prefix_lm
                    and self.cfg.positional != 'alibi')

    def _shared_prefix_split(self, ids: List[List[int]],
                             require_dominant: bool = False):
        """(prefix ids, suffix id rows) when the shared-prefix path
        applies to this batch, else (None, ids).  The prefix is rounded
        down to a _sp_quantum multiple (bounded jit shapes) and capped
        so every row keeps at least one suffix token.

        ``require_dominant``: engage only when the prefix is at least as
        long as the padded suffix bucket.  The scoring path's two-source
        attention materializes its score tensors (no flash kernel), so
        with a LONG suffix it loses to the plain flash forward — at 7B,
        label-outer MMLU batches (prefix 1280, suffix bucket 1024)
        measured 4.97 samples/s shared vs 6.52 plain, while
        short-suffix batches measured 2-3x wins.  get_ppl requires
        dominance; generate does not (prefill savings measured to win
        there even at long suffixes)."""
        if not self.shared_prefix_active or len(ids) < 2:
            return None, ids
        cp = self._common_prefix_len(ids)
        cap = min(len(r) for r in ids) - 1
        P = (min(cp, cap) // self._sp_quantum) * self._sp_quantum
        if P < self._sp_quantum:
            return None, ids
        if require_dominant:
            # mirror _pad_ids' bucket cap, else a round-up past
            # max_seq_len declines batches the padder would not pad that
            # far anyway
            s_bucket = _bucket(max(len(r) - P for r in ids),
                               hi=max(self.max_seq_len, 32))
            if P < s_bucket:
                return None, ids
        return ids[0][:P], [row[P:] for row in ids]

    def _encode_batch(self, inputs: List[str], left_pad: bool,
                      max_len: int, keep: str = 'head') -> tuple:
        """Tokenize + bucket-pad.  Returns (tokens, mask) int32/bool arrays
        of shape (bucket_batch, bucket_len).  ``keep`` picks which end
        survives truncation: 'head' (HF-parity default) or 'tail' (for
        scoring at the prompt end, e.g. CLP)."""
        ids = [self._encode_ids(str(s)) for s in inputs]
        ids = [(row[:max_len] if keep == 'head' else row[-max_len:])
               for row in ids]
        tokens, mask = self._pad_ids(ids, left_pad, max_len)
        spec = P('data', None)
        return self._put(tokens, spec), self._put(mask, spec), ids

    def plan_shape(self, n_rows: int, longest: int,
                   max_len: Optional[int] = None) -> tuple:
        """Padded device shape for a batch — the single source of truth
        shared by :meth:`_pad_ids` (what actually ships) and the batch
        planner (what it costs), so the two can never drift."""
        if max_len is None:
            max_len = self.max_seq_len
        S = _bucket(max(int(longest), 1), hi=max(int(max_len), 32))
        min_b = self.mesh.shape.get('data', 1) if self.mesh is not None else 1
        seq_par = self.mesh.shape.get('seq', 1) if self.mesh is not None \
            else 1
        if S % seq_par:  # ring attention shards S over the seq axis
            S = (S // seq_par + 1) * seq_par
        B = _bucket(max(int(n_rows), 1), lo=max(1, min_b))
        if B % min_b:  # non-pow2 data axis
            B = (B // min_b + 1) * min_b
        return B, S

    def _pad_ids(self, ids: List[List[int]], left_pad: bool,
                 max_len: int) -> tuple:
        """Bucket-pad pre-encoded id rows into (tokens, mask) numpy.
        Also charges the padding waste (pad slots actually materialized
        on device) to ``perf.pad_tokens`` — the padding-efficiency
        counter surfaced by the perf table and obs plane."""
        longest = max((len(x) for x in ids), default=1)
        B, S = self.plan_shape(len(ids), longest, max_len)
        self.perf.pad_tokens += B * S - sum(len(row) for row in ids)
        pad_id = self.tokenizer.pad_token_id or 0
        tokens = np.full((B, S), pad_id, np.int32)
        mask = np.zeros((B, S), bool)
        for i, row in enumerate(ids):
            if left_pad:
                tokens[i, S - len(row):] = row
                mask[i, S - len(row):] = True
            else:
                tokens[i, :len(row)] = row
                mask[i, :len(row)] = True
        return tokens, mask

    @functools.cached_property
    def _ppl_shared_fn(self):
        cfg = self.cfg

        @jax.jit
        def shared_nll(params, prefix, tokens, mask, ml):
            from opencompass_tpu.nn import shared_prefix_nll
            return shared_prefix_nll(params, cfg, prefix, tokens, mask,
                                     mask_length=ml)
        return shared_nll

    def get_ppl(self,
                inputs: List[str],
                mask_length: Optional[List[int]] = None) -> List[float]:
        return self.get_ppl_async(inputs, mask_length).result()

    def get_ppl_async(self,
                      inputs: List[str],
                      mask_length: Optional[List[int]] = None):
        """Tokenize, pad and enqueue one scoring batch; the returned
        handle's ``result()`` blocks on the device and copies the NLLs
        to host.  JAX dispatch is async, so the caller can prepare the
        next batch while this one executes (double buffering)."""
        with use_mesh(self.mesh):
            ids = [self._encode_ids(str(s))[:self.max_seq_len]
                   for s in inputs]
            prefix, rows = self._shared_prefix_split(ids,
                                                     require_dominant=True)
            ml = np.zeros((max(len(ids), 1),), np.int32)
            if mask_length is not None:
                ml[:len(mask_length)] = np.asarray(mask_length, np.int32)
            tokens, mask = self._pad_ids(rows, left_pad=False,
                                         max_len=self.max_seq_len)
            mlb = np.zeros((tokens.shape[0],), np.int32)
            mlb[:len(ml)] = ml
            first = self._first_dispatch(
                'ppl', prefix is not None and len(prefix), tokens.shape)
            cs0 = self.perf.compile_seconds
            info = self._tl_track('ppl', tokens.shape, first,
                                  sum(len(r) for r in ids))
            td0 = time.perf_counter()
            with device_call(self.perf,
                             tokens_in=sum(len(r) for r in ids),
                             samples=len(inputs), first=first):
                if prefix is not None:
                    spec = P('data', None)
                    nll = self._ppl_shared_fn(
                        self.params,
                        self._put(np.asarray(prefix, np.int32), P(None)),
                        self._put(tokens, spec), self._put(mask, spec),
                        self._put(mlb, P('data')))
                else:
                    spec = P('data', None)
                    nll = self._ppl_fn(self.params,
                                       self._put(tokens, spec),
                                       self._put(mask, spec),
                                       self._put(mlb, P('data')))
            if info is not None:
                info['dispatch_s'] = time.perf_counter() - td0
            if first and prefix is None:
                # shared-prefix executables are batch-content-dependent;
                # only plain-path shapes enter the manifest
                self._note_compile('ppl', tokens.shape,
                                   self.perf.compile_seconds - cs0,
                                   fn=self._ppl_fn,
                                   args=(self.params,
                                         self._put(tokens, spec),
                                         self._put(mask, spec),
                                         self._put(mlb, P('data'))))
        n = len(inputs)
        shape = list(tokens.shape)

        def fetch():
            t0 = time.perf_counter()
            with _step_scope('ppl', site='dense_fetch', shape=shape):
                out = np.asarray(nll)
            dt = time.perf_counter() - t0
            self.perf.device_seconds += dt
            if info is not None:
                info['fetch_s'] = dt
            return out[:n].tolist()
        return _Lazy(fetch)

    @functools.cached_property
    def _choice_logits_fn(self):
        """Jitted forward returning logits at each sequence's last real
        position (right-padded batch).  Uses ring attention when the mesh
        has a seq axis, same as the PPL path."""
        cfg = self.cfg
        mesh = self.mesh
        use_ring = mesh is not None and mesh.shape.get('seq', 1) > 1
        if use_ring:
            if cfg.prefix_lm:
                raise ValueError('prefix-LM choice scoring is not '
                                 'supported with sequence parallelism '
                                 '(ring attention is causal-blocked); use '
                                 'a data/model mesh')
            from opencompass_tpu.parallel.ring_attention import ring_forward

        @jax.jit
        def last_logits(params, tokens, mask):
            if use_ring:
                logits = ring_forward(params, cfg, tokens, mask, mesh)
            else:
                # prefix-LM (GLM): the whole prompt is bidirectional
                # context when scoring the next-token choice
                prefix = mask if cfg.prefix_lm else None
                logits = forward(params, cfg, tokens, mask,
                                 prefix_mask=prefix)
            last = jnp.maximum(
                jnp.sum(mask.astype(jnp.int32), axis=-1) - 1, 0)
            return self._replicate(jnp.take_along_axis(
                logits, last[:, None, None], axis=1)[:, 0, :])
        return last_logits

    def get_choice_logprobs(self, inputs: List[str],
                            choices: List[str]) -> List[List[float]]:
        """Softmax over the choices' first-token logits at the prompt end
        (the CLP measurement — reference icl_clp_inferencer.py:206-223)."""
        return self.get_choice_logprobs_async(inputs, choices).result()

    def get_choice_logprobs_async(self, inputs: List[str],
                                  choices: List[str]):
        choice_ids = []
        for choice in choices:
            # no specials here: we want the choice's own first token, not BOS
            ids = self.tokenizer.encode(str(choice),
                                        add_special_tokens=False)
            if not ids:
                raise ValueError(f'choice {choice!r} tokenizes to nothing')
            choice_ids.append(ids[0])
        with use_mesh(self.mesh):
            # keep the tail: the choice position is the prompt's end
            tokens, mask, ids = self._encode_batch(
                inputs, left_pad=False, max_len=self.max_seq_len,
                keep='tail')
            first = self._first_dispatch('choice', tokens.shape)
            cs0 = self.perf.compile_seconds
            info = self._tl_track('choice', tokens.shape, first,
                                  sum(len(r) for r in ids))
            td0 = time.perf_counter()
            with device_call(self.perf,
                             tokens_in=sum(len(r) for r in ids),
                             samples=len(inputs), first=first):
                logits = self._choice_logits_fn(self.params, tokens, mask)
            if info is not None:
                info['dispatch_s'] = time.perf_counter() - td0
            if first:
                self._note_compile('choice', tokens.shape,
                                   self.perf.compile_seconds - cs0,
                                   fn=self._choice_logits_fn,
                                   args=(self.params, tokens, mask))
        n = len(inputs)

        def fetch():
            t0 = time.perf_counter()
            logits_h = np.asarray(logits, np.float64)
            dt = time.perf_counter() - t0
            self.perf.device_seconds += dt
            if info is not None:
                info['fetch_s'] = dt
            sub = logits_h[:n][:, choice_ids]
            sub = np.exp(sub - sub.max(axis=-1, keepdims=True))
            sub = sub / sub.sum(axis=-1, keepdims=True)
            return sub.tolist()
        return _Lazy(fetch)

    # -- continuous batching ----------------------------------------------

    @property
    def continuous_eligible(self) -> bool:
        """Device-free half of :attr:`continuous_active`: flag on plus
        a config/decode-mode the paged step supports (no ALiBi /
        prefix-LM / beam search; int4-KV pools run the gather-fallback
        read path).  What ``cli plan`` and the warm-up shape census key
        on — a config this returns False for will run the dense path,
        so the dense B×S census must still be warmed."""
        if not self.continuous_batching or self.cfg is None:
            return False
        if self.cfg.positional == 'alibi' or self.cfg.prefix_lm:
            return False
        gk = self.generation_kwargs or {}
        return int(gk.get('num_beams', 1)) <= 1

    def kv_read_path(self) -> str:
        """Which KV-read path a continuous-engine step takes for this
        model: ``'ragged_kernel'`` (Pallas ragged-paged-attention over
        the pool pages) or ``'gather_fallback'`` (XLA gather of each
        slot's full table width).  Device-free host-side arithmetic —
        ``cli plan`` calls it on tokenizer_only models — and the same
        predicate ``nn/transformer.paged_step`` applies at trace time,
        so the plan/timeline label can never drift from the dispatch.
        ``ragged_kernel='auto'`` keeps the gather off-TPU (interpret-
        mode Pallas is correct but orders of magnitude too slow for a
        hot decode loop); ``'on'`` forces the kernel wherever
        ``ragged_kernel_active`` covers the config."""
        if self.ragged_kernel == 'off' or self.cfg is None:
            return 'gather_fallback'
        from opencompass_tpu.nn import transformer as _tf
        from opencompass_tpu.nn._platform import on_tpu
        if self.ragged_kernel == 'auto' and not on_tpu():
            return 'gather_fallback'
        mode = self.cfg.kv_quant_mode
        if mode == 'int8':
            k_dtype = jnp.int8
        elif mode == 'int4':
            k_dtype = 'int4'   # never kernel-supported; avoids jnp.int4
        else:
            k_dtype = jnp.dtype(self.cfg.dtype)
        with use_mesh(self.mesh):
            active = _tf.ragged_kernel_active(self.cfg, k_dtype)
        return 'ragged_kernel' if active else 'gather_fallback'

    @property
    def continuous_active(self) -> bool:
        """True when the continuous-batching engine can serve this
        model's generation: :attr:`continuous_eligible` plus weights
        resident and a mesh the engine step supports — none, a
        plain/data mesh (steps run un-meshed on the default device), or
        a tensor-parallel ('model') mesh when the ragged kernel covers
        it (the step is head-sharded via shard_map; the gather fallback
        stays single-device, so seq axes and multi-host stay out)."""
        if not self.continuous_eligible or self.tokenizer_only \
                or self.params is None:
            return False
        if self.mesh is None:
            return True
        if self._multihost() or self.mesh.shape.get('seq', 1) > 1:
            return False
        return (self.mesh.shape.get('model', 1) == 1
                or self.kv_read_path() == 'ragged_kernel')

    @property
    def speculative_eligible(self) -> bool:
        """Device-free gate for draft-model speculative decoding: a
        ``draft_model`` config is set, the continuous engine could run,
        and sampling is pure greedy (temperature 0, no top-k, one
        beam) — acceptance compares argmax ids, so anything stochastic
        falls back to the plain engine path."""
        if not self.draft_model or self.draft_k < 1 \
                or not self.continuous_eligible:
            return False
        temperature, top_k, _seed, num_beams, _lp = self._gen_params()
        return temperature <= 0.0 and top_k == 0 and num_beams == 1

    @property
    def speculative_active(self) -> bool:
        """:attr:`speculative_eligible` plus runtime conditions: the
        engine itself is active, the step runs un-meshed (none or a
        plain/data mesh — the tensor-parallel shard_map path has no
        draft/verify executables), and the draft's vocab matches the
        target's.  False here means the engine silently keeps its
        current (unspeculated) step — never an error."""
        if not self.speculative_eligible or not self.continuous_active:
            return False
        if self.mesh is not None and self.mesh.shape.get('model', 1) > 1:
            return False
        try:
            draft = self.draft_lm()
        except Exception as exc:       # unbuildable draft → fall back
            logger.warning('draft model unavailable, speculative '
                           'decoding disabled: %s', exc)
            return False
        return (draft.cfg is not None and self.cfg is not None
                and draft.cfg.vocab_size == self.cfg.vocab_size
                and draft.params is not None)

    def draft_lm(self) -> 'JaxLM':
        """The draft model, built once from the ``draft_model`` config
        dict (a JaxLM kwargs dict — e.g. ``dict(config='tiny')``).
        Inherits the target's max_seq_len unless overridden so both
        page pools cover the same positions."""
        if self._draft_lm is None:
            kw = dict(self.draft_model or {})
            kw.setdefault('max_seq_len', self.max_seq_len)
            self._draft_lm = JaxLM(**kw)
        return self._draft_lm

    def continuous_plan(self) -> Optional[Dict]:
        """Static engine geometry for the ``cli plan`` pre-flight:
        slot capacity, page sizing, the compile shapes a continuous
        sweep dispatches (ONE mixed prefill+decode step by default;
        the legacy ``mixed_step=False`` engine compiles two), and
        which KV-read path the step takes (``kv_read_path``:
        ragged_kernel vs gather_fallback).  Device-free — works on
        tokenizer_only models.  None when the engine is off."""
        if not self.continuous_batching:
            return None
        from opencompass_tpu.nn.paged_kv import (pages_per_seq,
                                                 pool_pages_for)
        slots, page = self.decode_slots, self.kv_page_size
        pages = int(self.kv_pool_pages or pool_pages_for(
            slots, self.max_seq_len, page))
        mixed = bool(getattr(self, 'continuous_mixed_step', True))
        plan = {
            'slots': slots,
            'page_size': page,
            'pool_pages': pages,
            'max_pages_per_seq': pages_per_seq(self.max_seq_len, page),
            'decode_shape': f'{slots}x1',
            'prefill_shape': f'{slots}x{page}',
            'mixed_step': mixed,
            'compile_shapes': 1 if mixed else 2,
            'kv_read_path': self.kv_read_path(),
        }
        if mixed:
            # T = page + 1 encodes the fused sub-batches (page-wide
            # prefill chunk + 1-wide decode) — the same key the compile
            # manifest / audit record for the engine's one executable
            plan['mixed_shape'] = f'{slots}x{page + 1}'
        # feature keys appear only when the knobs are on, so the base
        # geometry dict stays pinned by existing tests/tooling
        if self.prefix_cache:
            plan['prefix_cache'] = True
        if self.draft_model:
            plan['speculative'] = {
                'draft_k': self.draft_k,
                'eligible': bool(self.speculative_eligible),
                'verify_shape': f'{slots}x{self.draft_k + 1}',
            }
        return plan

    def continuous_engine(self) -> 'ContinuousEngine':
        """The resident engine (built on first use; rebuilt when the
        sampling parameters change, since they are static in its
        compiled step)."""
        if not self.continuous_active:
            raise RuntimeError('continuous batching is not active for '
                               'this model (see continuous_active)')
        key = self._gen_params()
        with self._cont_engine_lock:
            if self._cont_engine is None or self._cont_engine_key != key:
                self._cont_engine = ContinuousEngine(
                    self, slots=self.decode_slots,
                    page_size=self.kv_page_size,
                    num_pages=self.kv_pool_pages)
                self._cont_engine_key = key
            return self._cont_engine

    def generate_continuous(self, inputs: List[str], max_out_len: int,
                            on_result: Optional[Callable[[int, str],
                                                         None]] = None,
                            stats_out: Optional[Dict] = None,
                            interactive: bool = False,
                            on_token: Optional[Callable[[int, str, int],
                                                        None]] = None,
                            cancel_out: Optional[List] = None) \
            -> List[str]:
        """Generate through the continuous-batching engine: all rows
        enter the feed queue at once, join the resident decode step as
        slots free up, and retire individually — ``on_result(i, text)``
        fires per retired row (in retirement order), which is what lets
        the gen inferencer flush and tick progress per row instead of
        per batch.  Greedy outputs are token-identical to
        :meth:`generate` (pinned by tests/test_continuous_batching.py).
        ``stats_out``: optional dict filled with this call's
        prefill/decode token counts and measured time-to-first-token
        (the serve plane's TTFT SLO rides it).  ``interactive=True``
        routes the rows through the engine's priority lane — serve
        joins admit into free slots ahead of every queued sweep row,
        so an interactive completion never waits behind a sweep's
        prefill backlog.  ``on_token(i, piece, n_emitted)`` streams
        incremental text deltas per row as tokens land (fired from the
        stepping thread, outside the engine lock; concatenated pieces
        equal the row's final text whenever detokenization is
        prefix-monotone — unstable decodes hold a piece back until the
        next token resolves it).  ``cancel_out``: a list that receives
        one zero-arg callable cancelling this call's in-flight rows
        (client disconnect) — cancelled rows retire early with partial
        text.  Returns texts in input order."""
        from opencompass_tpu.icl.inferencers.schedule import \
            feed_queue_order
        engine = self.continuous_engine()
        max_new = int(max_out_len)
        max_prompt = max(self.max_seq_len - max_new, 32)
        with use_mesh(self.mesh):
            ids = [self._encode_ids(str(s))[:max_prompt] for s in inputs]
        texts: List[Optional[str]] = [None] * len(inputs)
        rows = []
        sent: Dict[int, str] = {}    # tag -> chars already streamed
        eos = self.eos_token_id

        def _stream_hook(row, _tok):
            toks = row.emitted if eos is None \
                else [t for t in row.emitted if t != eos]
            text = self.tokenizer.decode(toks)
            prev = sent.get(row.tag, '')
            # only emit when the running decode extends what was
            # already delivered — a mid-sequence flip (incomplete
            # multi-byte piece) holds back until a later token or the
            # final flush in deliver() resolves it
            if len(text) > len(prev) and text.startswith(prev):
                sent[row.tag] = text
                on_token(row.tag, text[len(prev):], len(row.emitted))

        hook = _stream_hook if on_token is not None else None
        for k in feed_queue_order([len(r) for r in ids]):
            if not ids[k] or max_new <= 0:
                texts[k] = ''
                if on_result is not None:
                    on_result(k, '')
                continue
            rows.append(engine.submit(ids[k], max_new, tag=k,
                                      interactive=interactive,
                                      on_token=hook))
        if cancel_out is not None:
            cancel_out.append(lambda: engine.cancel(rows))
        self.perf.tokens_in += sum(len(r) for r in ids)
        self.perf.samples += len(inputs)
        t0 = time.time()
        t0p = time.perf_counter()
        snap = engine.snapshot()

        def deliver(row):
            toks = row.emitted
            if self.eos_token_id is not None:
                toks = [t for t in toks if t != self.eos_token_id]
            self.perf.tokens_out += len(row.emitted)
            text = self.tokenizer.decode(toks)
            texts[row.tag] = text
            if on_token is not None:
                # final flush: anything detokenization held back (or
                # the EOS strip shortened) streams as one last piece
                prev = sent.get(row.tag, '')
                if len(text) > len(prev) and text.startswith(prev):
                    sent[row.tag] = text
                    on_token(row.tag, text[len(prev):],
                             len(row.emitted))
            if on_result is not None:
                on_result(row.tag, text)

        engine.drain(rows, deliver)
        # per-request inter-token latencies: consecutive emitted-token
        # gaps pooled over this call's rows (measured, not estimated —
        # the steady decode cadence next to TTFT's prefill cost)
        itl = [gap for row in rows for gap in row.itl_seconds()]
        itl_fields: Dict = {}
        if itl:
            from opencompass_tpu.obs.reqtrace import percentile
            from opencompass_tpu.obs.timeline import _downsample
            itl_fields = {
                'itl_p50_ms': round(percentile(itl, 0.50) * 1e3, 3),
                'itl_p99_ms': round(percentile(itl, 0.99) * 1e3, 3),
                'itl_ms': [round(v * 1e3, 3)
                           for v in _downsample(itl, 64)],
            }
        extra = {k: v for k, v in itl_fields.items() if k != 'itl_ms'}
        # host-side shared-prefix census of THIS drain (tokens all rows
        # share × reusing rows / total prompt tokens): the doctor's
        # prefix_waste rule compares this headroom against what the
        # trie actually saved, so it rides every engine record
        live = [r for r in ids if r]
        total_prompt = sum(len(r) for r in live)
        if len(live) > 1 and total_prompt:
            cp = len(os.path.commonprefix(live))
            extra['prefix_shareable_frac'] = round(
                cp * (len(live) - 1) / total_prompt, 4)
        self._record_engine_drain(engine, snap, len(rows), t0,
                                  extra=extra)
        if stats_out is not None:
            stats_out['prefill_tokens'] = sum(len(r) for r in ids)
            stats_out['decode_tokens'] = sum(
                len(r.emitted) for r in rows)
            cancelled = sum(1 for r in rows if r.cancelled)
            if cancelled:
                stats_out['cancelled_rows'] = cancelled
            try:
                es = engine.stats(since=snap)
                stats_out['prefill_tokens_saved'] = \
                    es.get('prefill_tokens_saved') or 0
                if es.get('spec_accept_rate') is not None:
                    stats_out['spec_accept_rate'] = \
                        es['spec_accept_rate']
            except Exception:
                pass
            stats_out.update(itl_fields)
            firsts = [r.first_token_ts for r in rows
                      if r.first_token_ts is not None]
            if firsts:
                # measured (not estimated): submit -> first sampled token
                stats_out['ttft_s'] = round(min(firsts) - t0p, 6)
            try:
                # roofline attribution for the serve plane: this
                # call's engine-step deltas → MFU/MBU against the
                # drain's device wall (requests.jsonl forward phase)
                cost = engine.cost_fields(engine.stats(since=snap))
                if cost.get('mfu') is not None:
                    stats_out['mfu'] = cost['mfu']
                if cost.get('mbu') is not None:
                    stats_out['mbu'] = cost['mbu']
            except Exception:
                pass
        return [t if t is not None else '' for t in texts]

    def _record_engine_drain(self, engine: 'ContinuousEngine',
                             snap: Dict, n_rows: int, t0: float,
                             extra: Optional[Dict] = None):
        """One flight-recorder ``engine`` record per drained call —
        per-drain DELTAS (this call's steps/joins/retires/occupancy),
        so a resident engine's Nth task reports only its own work
        (obs/timeline.py) — plus the drain's roofline fields
        (flops/bytes_w/bytes_kv[_ideal]/mfu/mbu from
        obs/costmodel.engine_cost, so the KV gather-vs-ideal traffic
        ratio rides every drain).  Never fails the call."""
        try:
            from opencompass_tpu.obs import get_timeline
            tl = get_timeline()
            if tl.enabled:
                stats = engine.stats(since=snap)
                fields = dict(stats, **engine.cost_fields(stats))
                fields.update(engine.profile_fields())
                if extra:
                    fields.update(extra)
                tl.engine('gen', ts=round(t0, 6), rows=n_rows,
                          dur_s=round(time.time() - t0, 6), **fields)
        except Exception:
            pass

    def generate(self, inputs: List[str], max_out_len: int) -> List[str]:
        return self.generate_async(inputs, max_out_len).result()

    def generate_async(self, inputs: List[str], max_out_len: int):
        if self.mesh is not None and self.mesh.shape.get('seq', 1) > 1 \
                and not getattr(self, '_warned_seq_gen', False):
            self._warned_seq_gen = True
            logger.warning(
                'generation does not use the seq (ring attention) axis; '
                'decode work is replicated across it — size the seq axis '
                'for scoring workloads, or use a data/model-only mesh for '
                'generation tasks')
        temperature, top_k, seed, num_beams, length_penalty = \
            self._gen_params()
        with use_mesh(self.mesh):
            max_prompt = max(self.max_seq_len - max_out_len, 32)
            ids = [self._encode_ids(str(s))[:max_prompt] for s in inputs]
            prefix, rows = (None, ids) if num_beams > 1 \
                else self._shared_prefix_split(ids)
            tokens, mask = self._pad_ids(rows, left_pad=True,
                                         max_len=max_prompt)
            first = self._first_dispatch(
                'gen', prefix is not None and len(prefix), tokens.shape,
                int(max_out_len), temperature, top_k, num_beams,
                length_penalty)
            cs0 = self.perf.compile_seconds
            info = self._tl_track('gen', tokens.shape, first,
                                  sum(len(r) for r in ids))
            td0 = time.perf_counter()
            with device_call(self.perf,
                             tokens_in=sum(len(r) for r in ids),
                             samples=len(inputs), first=first):
                rng = self._put(jax.random.PRNGKey(seed), P())
                if prefix is not None:
                    spec = P('data', None)
                    fn = self._gen_fn(int(max_out_len), temperature,
                                      top_k, prefixed=True)
                    out, lengths = fn(self.params,
                                      self._put(np.asarray(prefix,
                                                           np.int32),
                                                P(None)),
                                      self._put(tokens, spec),
                                      self._put(mask, spec), rng)
                else:
                    spec = P('data', None)
                    fn = self._gen_fn(int(max_out_len), temperature,
                                      top_k, num_beams, length_penalty)
                    out, lengths = fn(self.params,
                                      self._put(tokens, spec),
                                      self._put(mask, spec), rng)
            if info is not None:
                info['dispatch_s'] = time.perf_counter() - td0
            if first and prefix is None:
                self._note_compile('gen', tokens.shape,
                                   self.perf.compile_seconds - cs0,
                                   fn=fn,
                                   args=(self.params,
                                         self._put(tokens, spec),
                                         self._put(mask, spec), rng))
        n_in = len(inputs)
        shape = list(tokens.shape)

        def fetch():
            t0 = time.perf_counter()
            with _step_scope('gen', site='dense_fetch', shape=shape):
                out_h = np.asarray(out)
                lengths_h = np.asarray(lengths)
            dt = time.perf_counter() - t0
            self.perf.device_seconds += dt
            decode_tokens = int(lengths_h[:n_in].sum())
            if info is not None:
                # the fused prefill+decode executable gives no on-device
                # split; dispatch_s ≈ trace/compile + enqueue, fetch_s ≈
                # device wall, and the prefill/decode *token* split lets
                # the report reconstruct the cost structure
                info['fetch_s'] = dt
                info['decode_tokens'] = decode_tokens
            self.perf.tokens_out += decode_tokens
            texts = []
            for i in range(n_in):
                n = int(lengths_h[i])
                row = out_h[i, :n]
                if self.eos_token_id is not None:
                    row = row[row != self.eos_token_id]
                texts.append(self.tokenizer.decode(row))
            return texts
        return _Lazy(fetch)
