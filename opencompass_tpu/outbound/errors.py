"""Typed failure taxonomy for outbound API traffic.

Every way a remote provider can fail maps to exactly one
:class:`ProviderError` subclass, so the scheduler's policy table
(retry? back off? open the breaker? shed?) keys on ``kind`` instead of
string-matching exception text, and a failed row's durable record
(:class:`RowFailure`) names the failure the same way the operator docs
do (docs/user_guides/api_models.md, "Failure taxonomy").

``classify``/``from_http_error`` translate the raw transport layer
(urllib / socket / json) into this taxonomy at the single point where
HTTP happens (``BaseAPIModel.post_json_once``).
"""
from __future__ import annotations

import dataclasses
import json
import socket
from typing import Dict, List, Optional


class ProviderError(RuntimeError):
    """One failed request attempt against a remote provider.

    ``kind`` is the taxonomy key; ``retryable`` says whether another
    attempt could possibly succeed (auth and validation failures
    cannot); ``retry_after_s`` carries a provider-supplied pacing hint
    (the 429 ``Retry-After`` header) when one exists."""

    kind = 'provider_error'
    retryable = True

    def __init__(self, message: str, status: Optional[int] = None,
                 retry_after_s: Optional[float] = None):
        super().__init__(message)
        self.status = status
        self.retry_after_s = retry_after_s


class RateLimited(ProviderError):
    """HTTP 429: the provider is throttling.  Retryable, and the
    scheduler treats it as a *pacing* signal (AIMD backoff + global
    hold), not a provider fault — it never burns the breaker."""
    kind = 'rate_limited'


class ServerError(ProviderError):
    """HTTP 5xx: the provider itself failed.  Retryable with backoff;
    counts against the circuit breaker."""
    kind = 'server_error'


class NetworkError(ProviderError):
    """Connection-level failure (refused, reset, DNS).  Retryable;
    counts against the circuit breaker."""
    kind = 'network'


class StallError(ProviderError):
    """The request timed out in flight — the provider accepted the
    connection and then went quiet.  Retryable (and the hedging
    trigger); counts against the circuit breaker."""
    kind = 'stall'


class MalformedResponse(ProviderError):
    """2xx with a body that does not parse (truncated JSON, HTML error
    page behind a proxy).  Retryable; counts against the breaker."""
    kind = 'malformed_response'


class Rejected(ProviderError):
    """Non-429 4xx: auth failure or invalid request.  NOT retryable —
    the same bytes will fail the same way — and the scheduler's
    fail-fast path stops admitting sibling rows on it."""
    kind = 'rejected'
    retryable = False


class DeadlineExceeded(ProviderError):
    """The row's propagated wall budget died before (or while) the
    request could run.  Not retryable within this call."""
    kind = 'deadline_exceeded'
    retryable = False


class InternalError(ProviderError):
    """A client-side programmer error surfaced inside the transport
    hook (NotImplementedError, NameError, ...).  NOT retryable — the
    same code path fails the same way — and it must never feed the
    provider breaker: a local bug is not a provider incident."""
    kind = 'internal'
    retryable = False


def parse_retry_after(raw) -> Optional[float]:
    """Seconds from a ``Retry-After`` header value; ``None`` when
    absent or unparseable (HTTP-date forms are ignored — providers in
    this path send delta-seconds)."""
    try:
        val = float(raw)
    except (TypeError, ValueError):
        return None
    return val if val >= 0 else None


def from_http_error(err) -> ProviderError:
    """Map a ``urllib.error.HTTPError`` onto the taxonomy."""
    status = getattr(err, 'code', None) or 0
    reason = getattr(err, 'reason', '')
    headers = getattr(err, 'headers', None)
    retry_after = parse_retry_after(
        headers.get('Retry-After') if headers else None)
    if status == 429:
        return RateLimited(f'rate limited (429 {reason})', status=429,
                           retry_after_s=retry_after)
    if status in (408, 425):
        # transient by definition (request timeout / too early): a
        # retry can succeed — fail-fasting the sweep over one of
        # these would let a single slow request kill 1000 rows
        return StallError(f'provider timeout ({status} {reason})',
                          status=status, retry_after_s=retry_after)
    if 400 <= status < 500:
        return Rejected(f'provider rejected the request ({status} '
                        f'{reason})', status=status)
    return ServerError(f'provider error ({status} {reason})',
                       status=status, retry_after_s=retry_after)


def classify(exc: BaseException) -> ProviderError:
    """Map any transport-layer exception onto the taxonomy.  Already-
    typed errors pass through unchanged."""
    if isinstance(exc, ProviderError):
        return exc
    import urllib.error
    if isinstance(exc, urllib.error.HTTPError):
        return from_http_error(exc)
    if isinstance(exc, (socket.timeout, TimeoutError)):
        return StallError(f'request stalled: {exc}')
    if isinstance(exc, urllib.error.URLError):
        reason = getattr(exc, 'reason', exc)
        if isinstance(reason, (socket.timeout, TimeoutError)):
            return StallError(f'request stalled: {reason}')
        return NetworkError(f'network error: {reason}')
    if isinstance(exc, (json.JSONDecodeError, ValueError, KeyError,
                        TypeError, IndexError)):
        return MalformedResponse(f'unparseable provider response: '
                                 f'{type(exc).__name__}: {exc}')
    if isinstance(exc, (ConnectionError, OSError)):
        return NetworkError(f'network error: {exc}')
    if isinstance(exc, (NotImplementedError, NameError,
                        AttributeError, ImportError)):
        # a bug in the model's transport hook, not provider weather —
        # retrying or opening the breaker would misattribute it
        return InternalError(f'{type(exc).__name__}: {exc}')
    return ProviderError(f'{type(exc).__name__}: {exc}')


@dataclasses.dataclass
class RowFailure:
    """The durable, typed record of one row the scheduler could not
    complete.  Serialized into ``api_errors.json`` next to the task's
    predictions so a rerun (which recomputes exactly the missing rows
    via the idx-keyed ``tmp_`` resume) has the incident on disk."""
    index: int
    kind: str
    error: str
    attempts: int
    elapsed_s: float
    provider: str = ''

    def as_dict(self) -> Dict:
        return {'index': self.index, 'kind': self.kind,
                'error': self.error, 'attempts': self.attempts,
                'elapsed_s': round(self.elapsed_s, 3),
                'provider': self.provider}


class PartialFailure(RuntimeError):
    """Some rows failed after the scheduler exhausted their budgets.
    Successful siblings were still delivered (and flushed by the
    caller) — raising this marks the *task* failed-and-resumable, it
    does not unwind the finished work."""

    def __init__(self, failures: List[RowFailure], total: int,
                 provider: str = ''):
        self.failures = list(failures)
        self.total = int(total)
        self.provider = provider
        kinds: Dict[str, int] = {}
        for f in self.failures:
            kinds[f.kind] = kinds.get(f.kind, 0) + 1
        detail = ', '.join(f'{k} x{n}' for k, n in sorted(kinds.items()))
        first = self.failures[0].error if self.failures else ''
        super().__init__(
            f'{len(self.failures)}/{total} row(s) failed against '
            f'{provider or "provider"} ({detail}); first: {first}')
