"""Resilient outbound API scheduling (docs/user_guides/api_models.md).

All API-model traffic — batch sweeps, judge phases, interactive
completions — flows through one per-provider
:class:`~opencompass_tpu.outbound.scheduler.OutboundScheduler`:
AIMD-bounded concurrent in-flight requests, ``Retry-After``-honoring
adaptive pacing, retry budgets + deterministic-jitter backoff +
circuit breakers (the serve daemon's own primitives, shared via
``utils/resilience.py``), deadline propagation, optional hedged
requests, and typed per-row partial-failure records.  The
:class:`~opencompass_tpu.outbound.stub.StubProvider` is the
device-free fault-injecting endpoint under the tests, the
``cli chaos`` ``flaky_api`` scenario, and ``bench.py --outbound``.
"""
from .errors import (DeadlineExceeded, InternalError, MalformedResponse,
                     NetworkError, PartialFailure, ProviderError,
                     RateLimited, Rejected, RowFailure, ServerError,
                     StallError, classify, from_http_error,
                     parse_retry_after)
from .limits import AimdLimiter, Pacer
from .scheduler import (OUTBOUND_SNAPSHOT, Outcome, OutboundReport,
                        OutboundScheduler, all_stats, publish_snapshot,
                        read_outbound)
from .stub import StubProvider, canned_text

__all__ = [
    'AimdLimiter', 'DeadlineExceeded', 'InternalError',
    'MalformedResponse', 'NetworkError', 'OUTBOUND_SNAPSHOT',
    'Outcome', 'OutboundReport', 'OutboundScheduler', 'Pacer',
    'PartialFailure', 'ProviderError', 'RateLimited', 'Rejected',
    'RowFailure', 'ServerError', 'StallError', 'StubProvider',
    'all_stats', 'canned_text', 'classify', 'from_http_error',
    'parse_retry_after', 'publish_snapshot', 'read_outbound',
]
