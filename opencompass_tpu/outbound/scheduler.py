# oct-lint: clock-discipline
"""The outbound request scheduler: every API-model row flows through here.

Replaces the old per-call ``ThreadPoolExecutor`` + busy-thread QPS
bucket + synchronized ``2**attempt`` retry loop with one provider-aware
machine:

- **bounded adaptive concurrency** — an AIMD window
  (:class:`~opencompass_tpu.outbound.limits.AimdLimiter`) that backs
  off on 429/5xx and re-probes on success;
- **adaptive pacing** — a shared launch schedule that honors
  ``Retry-After`` globally (:class:`~.limits.Pacer`);
- **retry budgets + deterministic-jitter backoff + circuit breakers**
  — the *same* ``RetryBudget`` / ``backoff_delay`` / ``CircuitBreaker``
  implementations the serve daemon uses
  (``utils/resilience.py``);
- **deadline propagation** — an explicit per-call wall budget, or the
  serve path's ``X-OCT-Deadline-Ms`` remaining budget via
  ``reqtrace.current_deadline()``;
- **hedged requests** — a straggling attempt past ``hedge_after_s``
  launches one budgeted duplicate; first completion wins;
- **partial-failure scatter-back** — every row ends in exactly one
  :class:`Outcome`; failures are typed
  :class:`~opencompass_tpu.outbound.errors.RowFailure` records, and
  successes are delivered out-of-order through ``on_result`` as they
  land (the planner's scatter-back contract), so one dead row never
  unwinds its siblings.

All shared state is lock-guarded (``# guarded-by:``) and every time
read is injectable (``now=``) — the module is oct-lint
clock-discipline checked.
"""
from __future__ import annotations

import contextvars
import os
import threading
import time
import weakref
from typing import Callable, Dict, List, Optional, Sequence

from opencompass_tpu.utils.resilience import (CircuitBreaker,
                                              CircuitOpenError,
                                              RetryBudget, backoff_delay)

from .errors import (DeadlineExceeded, PartialFailure, ProviderError,
                     RateLimited, Rejected, RowFailure, classify)
from .limits import DEFAULT_MAX_INFLIGHT, AimdLimiter, Pacer

# outbound defaults: attempts per row (the model's `retry + 1` usually
# overrides), per-attempt HTTP timeout, and the retry backoff envelope
DEFAULT_MAX_ATTEMPTS = 3
DEFAULT_REQUEST_TIMEOUT_S = 60.0
OUTBOUND_BACKOFF_BASE_S = 0.25
OUTBOUND_BACKOFF_CAP_S = 8.0
# a 429 without a Retry-After header still holds the launch gate
DEFAULT_RETRY_AFTER_S = 0.5
# outbound retry budget: more generous than the serve protocol budget
# (API blips are common) but still bounded — an incident cannot turn
# every row into max_attempts requests
OUTBOUND_RETRY_RATE = 0.5       # tokens/second refill
OUTBOUND_RETRY_BURST = 8.0
# a row rides out an open breaker in-run only when the half-open
# horizon is this close; a longer cooldown sheds the row typed
# immediately (breaker_open, resumable) — a provider that is DOWN must
# fail a 1000-row sweep in seconds, not serialize every row through
# the cooldown
BREAKER_WAIT_CAP_S = 2.0
SNAPSHOT_INTERVAL_S = 2.0
OUTBOUND_SNAPSHOT = 'outbound.json'

# every live scheduler, for cross-provider snapshots (weak: a dropped
# model must not pin its scheduler forever)
_REGISTRY_LOCK = threading.Lock()
# guarded-by: _REGISTRY_LOCK
_SCHEDULERS: 'weakref.WeakSet' = weakref.WeakSet()

# the running row's absolute (monotonic) deadline, visible to the
# transport on *scheduler* threads — reqtrace's request context does
# not cross thread spawns, so the scheduler re-publishes the budget
# here and ``post_json_once`` forwards the remainder as
# ``X-OCT-Deadline-Ms`` on the outbound request
_ROW_DEADLINE: contextvars.ContextVar = contextvars.ContextVar(
    'oct_outbound_row_deadline', default=None)


def current_row_deadline_s(now: Optional[float] = None) \
        -> Optional[float]:
    """Remaining seconds of the running outbound row's deadline, when
    one is active on this thread; None otherwise."""
    deadline_ts = _ROW_DEADLINE.get()
    if deadline_ts is None:
        return None
    now = time.monotonic() if now is None else float(now)
    return max(deadline_ts - now, 0.0)


class Outcome:
    """One row's terminal result: either ``value`` (ok) or a typed
    ``failure``.  Exactly one Outcome exists per submitted row — the
    zero-silently-lost-rows invariant is structural."""
    __slots__ = ('index', 'value', 'failure', 'attempts', 'hedged')

    def __init__(self, index: int, value=None,
                 failure: Optional[RowFailure] = None,
                 attempts: int = 0, hedged: bool = False):
        self.index = index
        self.value = value
        self.failure = failure
        self.attempts = attempts
        self.hedged = hedged

    @property
    def ok(self) -> bool:
        return self.failure is None


class OutboundReport:
    """The result of one ``run``: per-row outcomes in submission order
    plus the scheduler counters measured across THIS run (counter
    deltas, so a scheduler shared across tasks attributes each task
    only its own 429s/retries) and the limiter/pacer/breaker state at
    run end."""

    def __init__(self, outcomes: List[Outcome], provider: str,
                 wall_s: float, stats: Dict):
        self.outcomes = outcomes
        self.provider = provider
        self.wall_s = wall_s
        self.stats = stats

    @property
    def failures(self) -> List[RowFailure]:
        return [o.failure for o in self.outcomes if o.failure]

    def values(self) -> List:
        """All row values, raising :class:`PartialFailure` if any row
        failed — the strict all-or-error contract ``generate`` keeps."""
        fails = self.failures
        if fails:
            raise PartialFailure(fails, len(self.outcomes),
                                 provider=self.provider)
        return [o.value for o in self.outcomes]


class OutboundScheduler:
    """Per-provider resilient request scheduler.

    ``run(payloads, call)`` drives every payload through bounded
    worker threads; ``call(payload, timeout_s)`` performs ONE attempt
    and raises typed :class:`ProviderError`\\ s (models supply
    ``post_json_once``-backed callables).  The scheduler owns retries,
    pacing, breaker routing, hedging, and deadline math.
    """

    def __init__(self, provider: str,
                 max_inflight: int = DEFAULT_MAX_INFLIGHT,
                 qps: Optional[float] = None,
                 max_attempts: int = DEFAULT_MAX_ATTEMPTS,
                 request_timeout_s: float = DEFAULT_REQUEST_TIMEOUT_S,
                 hedge_after_s: Optional[float] = None,
                 retry_budget: Optional[RetryBudget] = None,
                 breaker: Optional[CircuitBreaker] = None,
                 limiter: Optional[AimdLimiter] = None,
                 pacer: Optional[Pacer] = None,
                 sleep: Optional[Callable[[float], None]] = None):
        self.provider = provider or 'api'
        self.max_attempts = max(int(max_attempts), 1)
        self.request_timeout_s = float(request_timeout_s)
        self.hedge_after_s = hedge_after_s
        self.limiter = limiter or AimdLimiter(max_limit=max_inflight)
        self.pacer = pacer or Pacer(qps=qps)
        self.budget = retry_budget or RetryBudget(
            rate=OUTBOUND_RETRY_RATE, burst=OUTBOUND_RETRY_BURST)
        self.breaker = breaker or CircuitBreaker(self.provider)
        self._sleep = sleep or time.sleep
        self._lock = threading.Lock()
        # guarded-by: _lock
        self._counters: Dict[str, int] = {
            'rows_total': 0, 'ok_total': 0, 'failed_total': 0,
            'attempts_total': 0, 'retries_total': 0,
            'retry_budget_refusals': 0, 'http_429_total': 0,
            'http_5xx_total': 0, 'hedges_total': 0,
            'hedge_wins_total': 0, 'breaker_opens_total': 0,
            'breaker_sheds_total': 0, 'deadline_failures_total': 0,
        }
        # guarded-by: _lock — rolling launch timestamps for the
        # measured-qps gauge
        self._launch_ts: List[float] = []
        # guarded-by: _lock
        self._last_event_ts: Optional[float] = None
        # guarded-by: _lock
        self._last_snapshot_ts: Optional[float] = None
        with _REGISTRY_LOCK:
            _SCHEDULERS.add(self)

    # -- public API ---------------------------------------------------------

    def run(self, payloads: Sequence, call: Callable,
            on_result: Optional[Callable[[int, object], None]] = None,
            deadline_s: Optional[float] = None,
            fail_fast: bool = True) -> OutboundReport:
        """Drive every payload to a terminal :class:`Outcome`.

        ``on_result(index, value)`` fires per successful row, from
        scheduler threads, in completion order — the scatter-back
        hook.  ``deadline_s`` bounds the whole run's wall clock; when
        None and a serve-path request deadline is active
        (``X-OCT-Deadline-Ms``), the remaining budget is inherited.
        ``fail_fast`` stops admitting new rows once a non-retryable
        (rejected) failure proves the endpoint dead — in-flight rows
        drain, queued rows fail typed ``aborted``."""
        t0 = time.monotonic()
        if deadline_s is None:
            deadline_s = serve_deadline_remaining_s()
        deadline_ts = None if deadline_s is None \
            else t0 + max(float(deadline_s), 0.0)
        with self._lock:
            self._counters['rows_total'] += len(payloads)
            counters_at_start = dict(self._counters)
        outcomes: List[Optional[Outcome]] = [None] * len(payloads)
        order = list(range(len(payloads)))
        state = {'next': 0, 'fatal': None}
        state_lock = threading.Lock()

        def worker():
            while True:
                with state_lock:
                    if state['next'] >= len(order):
                        return
                    i = order[state['next']]
                    state['next'] += 1
                    fatal = state['fatal']
                if fatal is not None:
                    # fail-fast drain: the endpoint is provably dead
                    # (auth/validation) — queued rows become typed,
                    # resumable failures instead of more requests
                    outcomes[i] = Outcome(i, failure=RowFailure(
                        index=i, kind='aborted',
                        error=f'aborted after fatal sibling failure: '
                              f'{fatal}',
                        attempts=0, elapsed_s=0.0,
                        provider=self.provider))
                    continue
                outcome = self._run_row(i, payloads[i], call,
                                        deadline_ts, state, state_lock,
                                        fail_fast)
                outcomes[i] = outcome
                if outcome.ok and on_result is not None:
                    try:
                        on_result(i, outcome.value)
                    except Exception as exc:   # noqa: BLE001
                        # a broken collector (disk full on the flush,
                        # a bug in the save hook) means this row was
                        # NOT persisted: it must surface as a typed
                        # failure — an ok outcome here would finalize
                        # the task with the row silently missing
                        outcomes[i] = Outcome(i, failure=RowFailure(
                            index=i, kind='collector_error',
                            error=f'result collector failed: {exc}',
                            attempts=outcome.attempts, elapsed_s=0.0,
                            provider=self.provider),
                            attempts=outcome.attempts)
                        with state_lock:
                            if state['fatal'] is None:
                                state['fatal'] = exc

        n_threads = max(1, min(len(payloads), self.limiter.max_limit))
        threads = [threading.Thread(target=worker,
                                    name=f'outbound-{self.provider}-{k}',
                                    daemon=True)
                   for k in range(n_threads)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        ok = sum(1 for o in outcomes if o is not None and o.ok)
        with self._lock:
            self._counters['ok_total'] += ok
            self._counters['failed_total'] += len(payloads) - ok
        self._publish(force=True)
        run_stats = self.stats()
        for key, start in counters_at_start.items():
            if isinstance(run_stats.get(key), int):
                run_stats[key] -= start
        run_stats['rows_total'] = len(payloads)
        return OutboundReport(
            [o if o is not None else Outcome(i, failure=RowFailure(
                index=i, kind='aborted', error='row never scheduled',
                attempts=0, elapsed_s=0.0, provider=self.provider))
             for i, o in enumerate(outcomes)],
            self.provider, time.monotonic() - t0, run_stats)

    def stats(self, now: Optional[float] = None) -> Dict:
        now = time.monotonic() if now is None else float(now)
        with self._lock:
            counters = dict(self._counters)
            cutoff = now - 10.0
            self._launch_ts = [t for t in self._launch_ts
                               if t >= cutoff]
            qps = len(self._launch_ts) / 10.0
        out = dict(counters)
        out['provider'] = self.provider
        out['measured_qps'] = round(qps, 2)
        out['limiter'] = self.limiter.snapshot()
        out['pacer'] = self.pacer.snapshot(now=now)
        out['breaker'] = self.breaker.snapshot(now=now)
        return out

    # -- row state machine --------------------------------------------------

    def _run_row(self, i: int, payload, call, deadline_ts, state,
                 state_lock, fail_fast: bool = True) -> Outcome:
        t_row = time.monotonic()
        attempts = 0
        hedged = False
        last_err: Optional[ProviderError] = None
        while True:
            now = time.monotonic()
            remaining = None if deadline_ts is None \
                else deadline_ts - now
            if remaining is not None and remaining <= 0:
                self._count('deadline_failures_total')
                detail = f' (last error: {last_err})' if last_err else ''
                return self._failure(
                    i, 'deadline_exceeded', attempts, t_row,
                    f'deadline exhausted after {attempts} '
                    f'attempt(s){detail}')
            if attempts >= self.max_attempts:
                return self._failure(
                    i, last_err.kind if last_err else 'provider_error',
                    attempts, t_row,
                    f'request failed after {attempts} attempts: '
                    f'{last_err}')
            attempts += 1
            self._count('attempts_total')
            # breaker gate: a near half-open horizon (short cooldown)
            # is ridden out in-run so the probe can recover the sweep;
            # a far one sheds the row typed IMMEDIATELY — failed rows
            # are resumable records, and a dead endpoint must fail the
            # sweep in seconds, not serialize rows through cooldowns
            try:
                self.breaker.allow()
            except CircuitOpenError as exc:
                last_err = last_err or ProviderError(str(exc))
                wait = exc.retry_after_s
                if wait > BREAKER_WAIT_CAP_S \
                        or (remaining is not None
                            and wait >= remaining) \
                        or attempts >= self.max_attempts:
                    # counted only when the row is actually shed —
                    # riding out a short cooldown is not a shed
                    self._count('breaker_sheds_total')
                    return self._failure(
                        i, 'breaker_open', attempts, t_row,
                        f'request failed after {attempts} attempts: '
                        f'{exc}')
                self._sleep(wait)
                continue
            if not self.limiter.acquire(timeout=remaining):
                self._count('deadline_failures_total')
                return self._failure(
                    i, 'deadline_exceeded', attempts, t_row,
                    f'deadline exhausted waiting for an in-flight '
                    f'slot after {attempts - 1} attempt(s)')
            err: Optional[ProviderError] = None
            # the acquired slot is released exactly once, in the
            # finally — UNLESS _call_hedged handed ownership to an
            # abandoned still-in-flight attempt (its request keeps the
            # slot until it actually ends, so true concurrency never
            # exceeds the AIMD window)
            slot = {'caller_releases': True}
            try:
                delay = self.pacer.reserve()
                if delay > 0:
                    if remaining is not None \
                            and delay >= remaining:
                        self._count('deadline_failures_total')
                        return self._failure(
                            i, 'deadline_exceeded', attempts, t_row,
                            f'deadline exhausted in the pacing queue '
                            f'(hold {delay:.2f}s)')
                    self._sleep(delay)
                with self._lock:
                    self._launch_ts.append(time.monotonic())
                timeout = self.request_timeout_s
                if deadline_ts is not None:
                    timeout = max(0.05,
                                  min(timeout,
                                      deadline_ts - time.monotonic()))
                value, row_hedged = self._call_hedged(
                    payload, call, timeout, deadline_ts, slot)
                hedged = hedged or row_hedged
            except BaseException as exc:   # noqa: BLE001 — classified
                err = classify(exc)
            finally:
                if slot['caller_releases']:
                    self.limiter.release()
            if err is None:
                self.limiter.on_success()
                self.breaker.note_success()
                self._publish()
                return Outcome(i, value=value, attempts=attempts,
                               hedged=hedged)
            last_err = err
            verdict = self._note_error(err)
            if not err.retryable:
                if fail_fast and isinstance(err, Rejected):
                    with state_lock:
                        if state['fatal'] is None:
                            state['fatal'] = err
                kind = err.kind
                return self._failure(
                    i, kind, attempts, t_row,
                    f'request failed after {attempts} attempts: {err}')
            if attempts >= self.max_attempts:
                continue   # the loop head renders the terminal failure
            if not self.budget.take(self.provider):
                self._count('retry_budget_refusals')
                return self._failure(
                    i, err.kind, attempts, t_row,
                    f'retry budget exhausted after {attempts} '
                    f'attempt(s): {err}')
            self._count('retries_total')
            delay = backoff_delay(f'{self.provider}#{i}', attempts - 1,
                                  base_s=OUTBOUND_BACKOFF_BASE_S,
                                  cap_s=OUTBOUND_BACKOFF_CAP_S)
            if verdict is not None:
                delay = max(delay, verdict)
            if remaining is not None:
                now = time.monotonic()
                if deadline_ts - now <= delay:
                    self._count('deadline_failures_total')
                    return self._failure(
                        i, 'deadline_exceeded', attempts, t_row,
                        f'deadline exhausted before retry '
                        f'{attempts + 1} (backoff {delay:.2f}s, '
                        f'last error: {err})')
            self._sleep(delay)

    def _note_error(self, err: ProviderError) -> Optional[float]:
        """Fold one typed failure into the adaptive state; returns a
        minimum backoff the provider demanded (Retry-After), if any."""
        if isinstance(err, RateLimited):
            self._count('http_429_total')
            self.limiter.on_throttle()
            hold = err.retry_after_s if err.retry_after_s is not None \
                else DEFAULT_RETRY_AFTER_S
            self.pacer.hold(hold)
            self._event('outbound_throttled',
                        retry_after_s=err.retry_after_s)
            return hold
        if isinstance(err, DeadlineExceeded):
            self._count('deadline_failures_total')
            return None
        if isinstance(err, Rejected) or err.kind == 'internal':
            # client-side causes: neither breaker evidence nor a
            # pacing signal
            return None
        # server_error / network / stall / malformed: provider-fault
        # family — breaker evidence, and 5xx also backs off the window
        if err.kind == 'server_error':
            self._count('http_5xx_total')
            self.limiter.on_throttle()
        opened = self.breaker.note_failure(str(err))
        if opened:
            self._count('breaker_opens_total')
            self._event('outbound_breaker_open', error=str(err)[:200],
                        force=True)
        return err.retry_after_s

    def _failure(self, i: int, kind: str, attempts: int, t_row: float,
                 error: str) -> Outcome:
        failure = RowFailure(index=i, kind=kind, error=error,
                             attempts=attempts,
                             elapsed_s=time.monotonic() - t_row,
                             provider=self.provider)
        self._publish()
        return Outcome(i, failure=failure, attempts=attempts)

    # -- hedging ------------------------------------------------------------

    def _call_hedged(self, payload, call, timeout: float,
                     deadline_ts: Optional[float], slot: Dict):
        """One logical request, optionally hedged: when the primary
        attempt is still in flight after ``hedge_after_s`` and both a
        spare in-flight slot and a retry-budget token exist, a
        duplicate launches; the first completion wins.  A loser is
        abandoned to its timeout (urllib cannot be cancelled) but
        keeps holding its in-flight slot until its request actually
        ends: the primary rides the caller's slot (ownership handed
        over via ``slot['caller_releases']``), the hedge owns the one
        it acquired — so true concurrency never exceeds the AIMD
        window."""
        if self.hedge_after_s is None:
            return self._call_one(payload, call, timeout,
                                  deadline_ts), False
        cond = threading.Condition()
        # (is_hedge, ok, value_or_exc) per finished attempt and the
        # primary-slot transfer flag, all mutated under cond
        results: List = []
        launched = [1]
        transfer = [False]

        def attempt(is_hedge: bool):
            try:
                res = (is_hedge, True,
                       self._call_one(payload, call, timeout,
                                      deadline_ts))
            except BaseException as exc:   # noqa: BLE001
                res = (is_hedge, False, exc)
            finally:
                if is_hedge:
                    self.limiter.release()
            with cond:
                results.append(res)
                cond.notify_all()
                if not is_hedge and transfer[0]:
                    # the row's caller already moved on: the abandoned
                    # primary owns the row slot, and its request just
                    # ended — free it now
                    self.limiter.release()

        threading.Thread(target=attempt, args=(False,),
                         name=f'outbound-{self.provider}-primary',
                         daemon=True).start()
        with cond:
            cond.wait_for(lambda: bool(results),
                          timeout=self.hedge_after_s)
            straggling = not results
        if straggling and self.limiter.acquire(timeout=0):
            if self.budget.take(self.provider):
                self._count('hedges_total')
                delay = self.pacer.reserve()
                if delay > 0:
                    self._sleep(delay)
                launched[0] = 2
                threading.Thread(
                    target=attempt, args=(True,),
                    name=f'outbound-{self.provider}-hedge',
                    daemon=True).start()
            else:
                self.limiter.release()

        def finish(result=None, error=None):
            # one exit point: if the primary is still in flight, hand
            # it the row slot before the caller's finally would free it
            if not any(not h for h, _, _ in results):
                transfer[0] = True
                slot['caller_releases'] = False
            if error is not None:
                raise error
            return result

        with cond:
            done = cond.wait_for(
                lambda: any(ok for _, ok, _ in results)
                or len(results) >= launched[0],
                timeout=timeout + 5.0)
            if not done and not results:
                from .errors import StallError
                return finish(error=StallError(
                    f'request stalled past {timeout:.0f}s '
                    '(hedge included)'))
            for is_hedge, ok, res in results:
                if ok:
                    if is_hedge:
                        # exact accounting: credited only when the
                        # hedge attempt actually produced the result
                        self._count('hedge_wins_total')
                    return finish(result=(res, is_hedge))
            return finish(error=results[0][2])

    @staticmethod
    def _call_one(payload, call, timeout: float,
                  deadline_ts: Optional[float]):
        """One transport attempt with the row deadline published on
        THIS thread (hedge helpers included), so ``post_json_once``
        forwards the remaining budget outbound."""
        if deadline_ts is None:
            return call(payload, timeout)
        token = _ROW_DEADLINE.set(deadline_ts)
        try:
            return call(payload, timeout)
        finally:
            _ROW_DEADLINE.reset(token)

    # -- telemetry ----------------------------------------------------------

    def _count(self, key: str, n: int = 1):
        with self._lock:
            self._counters[key] = self._counters.get(key, 0) + n

    def _event(self, name: str, force: bool = False, **attrs):
        """Structured obs event, rate-limited to one per 5s per
        scheduler unless ``force`` (breaker transitions always
        land)."""
        try:
            now = time.monotonic()
            if not force:
                with self._lock:
                    last = self._last_event_ts
                    if last is not None and now - last < 5.0:
                        return
                    self._last_event_ts = now
            from opencompass_tpu.obs import get_tracer
            tracer = get_tracer()
            if tracer.enabled:
                tracer.event(name, provider=self.provider,
                             **{k: v for k, v in attrs.items()
                                if v is not None})
        except Exception:   # noqa: BLE001 — never-fail telemetry
            pass

    def _publish(self, force: bool = False, now: Optional[float] = None):
        """Push the outbound family onto the metrics registry and the
        durable ``outbound.json`` snapshot, rate-limited."""
        mono = time.monotonic()
        with self._lock:
            last = self._last_snapshot_ts
            if not force and last is not None \
                    and mono - last < SNAPSHOT_INTERVAL_S:
                return
            self._last_snapshot_ts = mono
        try:
            self._publish_metrics()
        except Exception:   # noqa: BLE001 — never-fail telemetry
            pass
        try:
            publish_snapshot(now=now)
        except Exception:   # noqa: BLE001 — never-fail telemetry
            pass

    def _publish_metrics(self):
        from opencompass_tpu.obs import get_tracer
        from opencompass_tpu.obs.metrics import labeled
        tracer = get_tracer()
        if not tracer.enabled:
            return
        stats = self.stats()
        reg = tracer.metrics
        label = {'provider': self.provider}
        reg.gauge(labeled('oct_outbound_inflight', **label)).set(
            stats['limiter']['inflight'])
        reg.gauge(labeled('oct_outbound_limit', **label)).set(
            stats['limiter']['limit'])
        reg.gauge(labeled('oct_outbound_qps', **label)).set(
            stats['measured_qps'])
        breaker_state = {'closed': 0, 'open': 1,
                         'half_open': 2}.get(
                             stats['breaker']['state'], 0)
        reg.gauge(labeled('oct_outbound_breaker_state',
                          **label)).set(breaker_state)
        for key in ('http_429_total', 'retries_total', 'hedges_total',
                    'attempts_total', 'failed_total'):
            reg.gauge(labeled(f'oct_outbound_{key}', **label)).set(
                stats[key])


# -- cross-scheduler snapshot ------------------------------------------------

def all_stats() -> Dict[str, Dict]:
    """Current stats for every live scheduler, keyed by provider
    (same-provider schedulers fold by max-counter wins)."""
    with _REGISTRY_LOCK:
        schedulers = list(_SCHEDULERS)
    out: Dict[str, Dict] = {}
    for sched in schedulers:
        try:
            stats = sched.stats()
        except Exception:   # noqa: BLE001
            continue
        prev = out.get(sched.provider)
        if prev is None or stats.get('attempts_total', 0) \
                >= prev.get('attempts_total', 0):
            out[sched.provider] = stats
    return out


def snapshot_dirs() -> List[str]:
    """Where the durable outbound snapshot lands: the live tracer's
    obs dir (batch runs), plus the serve obs dir when a cache root is
    in the environment (daemon / worker context)."""
    dirs: List[str] = []
    try:
        from opencompass_tpu.obs import get_tracer
        tracer = get_tracer()
        if tracer.enabled and getattr(tracer, 'obs_dir', None):
            dirs.append(tracer.obs_dir)
    except Exception:   # noqa: BLE001
        pass
    cache_root = os.environ.get('OCT_CACHE_ROOT')
    if cache_root:
        try:
            from opencompass_tpu.obs.reqtrace import serve_obs_dir
            serve_dir = serve_obs_dir(cache_root)
            if os.path.isdir(serve_dir):
                dirs.append(serve_dir)
        except Exception:   # noqa: BLE001
            pass
    return dirs


def publish_snapshot(now: Optional[float] = None) -> Optional[Dict]:
    """Write the cross-provider snapshot (``outbound.json``) wherever
    observers look — ``cli top``'s outbound pane and ``cli doctor``'s
    ``api_throttled`` rule read this file, dead process or live."""
    providers = all_stats()
    if not providers:
        return None
    snap = {'v': 1,
            'ts': time.time() if now is None else float(now),
            'pid': os.getpid(),
            'providers': providers}
    from opencompass_tpu.utils.fileio import atomic_write_json
    for dirpath in snapshot_dirs():
        try:
            atomic_write_json(
                os.path.join(dirpath, OUTBOUND_SNAPSHOT), snap,
                dump_kwargs={'indent': 2, 'default': str})
        except Exception:   # noqa: BLE001 — never-fail telemetry
            pass
    return snap


def read_outbound(dirpath: str) -> Optional[Dict]:
    """Load a durable outbound snapshot; None when absent/torn."""
    import json
    try:
        with open(os.path.join(dirpath, OUTBOUND_SNAPSHOT),
                  encoding='utf-8') as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def serve_deadline_remaining_s() -> Optional[float]:
    """The serve path's remaining request budget, when this call is
    running under an ``X-OCT-Deadline-Ms`` request context — the ONE
    lookup both the scheduler's run-deadline inheritance and
    ``post_json_once``'s header forwarding share."""
    try:
        from opencompass_tpu.obs.reqtrace import current_deadline
        deadline = current_deadline()
        if deadline is None:
            return None
        return max(deadline.remaining_s(), 0.0)
    except Exception:   # noqa: BLE001
        return None
