"""Device-free fault-injecting local API provider.

A real (loopback) OpenAI-compatible HTTP server whose behavior is a
set of thread-safe knobs: injected 429 bursts with ``Retry-After``
headers, hard 500s, auth 401s, stalls, malformed JSON bodies,
per-request latency, and content-targeted failures (``fail_marker``)
for partial-failure drills.  Responses are **deterministic functions
of the prompt**, so convergence checks ("the resumed rerun is
bit-identical to a clean run") are exact.

This is the substrate under the outbound scheduler's tests, the
``cli chaos`` ``flaky_api`` scenario, and the ``bench.py --outbound``
leg — the same role ``FakeModel`` plays for the device path.
"""
from __future__ import annotations

import hashlib
import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional


def canned_text(prompt: str) -> str:
    """The stub's deterministic completion for a prompt."""
    digest = hashlib.sha256(str(prompt).encode()).hexdigest()[:8]
    return f'ok[{digest}] {str(prompt)[:48]}'


def _prompt_of(body: Dict) -> str:
    if isinstance(body.get('messages'), list):
        users = [m.get('content', '') for m in body['messages']
                 if isinstance(m, dict)]
        return users[-1] if users else ''
    return str(body.get('prompt', ''))


class StubProvider:
    """One loopback provider with scriptable faults.

    Knobs (all thread-safe, liftable mid-flight):

    - ``set_latency(s)``: per-request service time.
    - ``queue_429(n, retry_after_s)``: the next ``n`` requests answer
      429 (with a ``Retry-After`` header when given).
    - ``set_429_every(k, retry_after_s)``: every ``k``-th request
      answers 429 — the steady throttle mix for bench sweeps.
    - ``set_mode(m)``: ``None`` (healthy) | ``'500'`` | ``'401'`` |
      ``'stall'`` | ``'malformed'``.
    - ``set_fail_marker(substr)``: requests whose prompt contains
      ``substr`` answer 500 — row-targeted partial failure.
    """

    def __init__(self, latency_s: float = 0.0, stall_s: float = 30.0):
        self._lock = threading.Lock()
        # guarded-by: _lock
        self._latency_s = float(latency_s)
        # guarded-by: _lock
        self._stall_s = float(stall_s)
        # guarded-by: _lock
        self._queued_429 = 0
        # guarded-by: _lock
        self._retry_after_s: Optional[float] = None
        # guarded-by: _lock
        self._every_429 = 0
        # guarded-by: _lock
        self._mode: Optional[str] = None
        # guarded-by: _lock  (bumped on every set_mode — stalled
        # handlers re-check it so lifting the fault frees them)
        self._mode_gen = 0
        # guarded-by: _lock
        self._fail_marker: Optional[str] = None
        # guarded-by: _lock
        self._queued_stall = 0
        # guarded-by: _lock
        self._inflight = 0
        # guarded-by: _lock
        self._counters = {'requests_total': 0, 'http_429': 0,
                          'http_500': 0, 'http_401': 0, 'stalls': 0,
                          'malformed': 0, 'ok': 0,
                          'max_concurrent': 0}
        # guarded-by: _lock
        self._log: List[Dict] = []
        self._server: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> 'StubProvider':
        provider = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = 'HTTP/1.1'

            def log_message(self, *args):
                pass

            def do_POST(self):
                try:
                    provider._handle(self)
                except (ConnectionError, OSError):
                    # a stalled/slow handler answering a client that
                    # already timed out — the drill, not a bug
                    self.close_connection = True

        class Server(ThreadingHTTPServer):
            def handle_error(self, request, client_address):
                pass   # same: dead-client noise stays off stderr

        self._server = Server(('127.0.0.1', 0), Handler)
        self._server.daemon_threads = True
        self._thread = threading.Thread(
            target=self._server.serve_forever,
            name='outbound-stub-provider', daemon=True)
        self._thread.start()
        return self

    def stop(self):
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None

    @property
    def port(self) -> int:
        return self._server.server_address[1]

    @property
    def url(self) -> str:
        return f'http://127.0.0.1:{self.port}'

    @property
    def chat_url(self) -> str:
        return self.url + '/v1/chat/completions'

    @property
    def completions_url(self) -> str:
        return self.url + '/v1/completions'

    # -- knobs --------------------------------------------------------------

    def set_latency(self, seconds: float):
        with self._lock:
            self._latency_s = float(seconds)

    def queue_429(self, n: int, retry_after_s: Optional[float] = None):
        with self._lock:
            self._queued_429 += int(n)
            self._retry_after_s = retry_after_s

    def set_429_every(self, k: int,
                      retry_after_s: Optional[float] = None):
        with self._lock:
            self._every_429 = int(k)
            self._retry_after_s = retry_after_s

    def set_mode(self, mode: Optional[str]):
        assert mode in (None, '500', '401', 'stall', 'malformed')
        with self._lock:
            self._mode = mode
            self._mode_gen += 1

    def set_stall_s(self, seconds: float):
        with self._lock:
            self._stall_s = float(seconds)

    def set_fail_marker(self, marker: Optional[str]):
        with self._lock:
            self._fail_marker = marker

    def queue_stall(self, n: int):
        """The next ``n`` requests stall (straggler injection — the
        hedging drill's targeted variant of ``set_mode('stall')``)."""
        with self._lock:
            self._queued_stall += int(n)

    # -- introspection ------------------------------------------------------

    def stats(self) -> Dict:
        with self._lock:
            return dict(self._counters, inflight=self._inflight)

    def log(self) -> List[Dict]:
        with self._lock:
            return list(self._log)

    def reset_stats(self):
        with self._lock:
            for key in self._counters:
                self._counters[key] = 0
            self._log.clear()

    # -- request handling ---------------------------------------------------

    def _decide(self, prompt: str):
        """One atomic admission decision: (status, retry_after, mode,
        mode_gen) — and the counters/log update that goes with it."""
        with self._lock:
            self._counters['requests_total'] += 1
            self._inflight += 1
            self._counters['max_concurrent'] = max(
                self._counters['max_concurrent'], self._inflight)
            n_req = self._counters['requests_total']
            mode, gen = self._mode, self._mode_gen
            retry_after = self._retry_after_s
            status = 200
            if self._queued_429 > 0:
                self._queued_429 -= 1
                status = 429
            elif self._every_429 and n_req % self._every_429 == 0:
                status = 429
            elif self._fail_marker and self._fail_marker in prompt:
                status = 500
                mode = None
            elif mode == '500':
                status = 500
            elif mode == '401':
                status = 401
            if status == 429:
                self._counters['http_429'] += 1
            elif status == 500:
                self._counters['http_500'] += 1
            elif status == 401:
                self._counters['http_401'] += 1
            stall = status == 200 and mode == 'stall'
            if status == 200 and self._queued_stall > 0:
                self._queued_stall -= 1
                stall = True
            return (status, retry_after, mode, gen,
                    self._latency_s, self._stall_s, stall)

    def _mode_still(self, gen: int) -> bool:
        with self._lock:
            return self._mode_gen == gen

    def _handle(self, handler: BaseHTTPRequestHandler):
        t_in = time.monotonic()
        try:
            length = int(handler.headers.get('Content-Length') or 0)
            try:
                body = json.loads(handler.rfile.read(length) or b'{}')
            except ValueError:
                body = {}
            prompt = _prompt_of(body)
            (status, retry_after, mode, gen, latency, stall_s,
             stall) = self._decide(prompt)
            if latency:
                time.sleep(latency)
            if stall:
                with self._lock:
                    self._counters['stalls'] += 1
                # sliced sleep: lifting the fault (set_mode) frees
                # already-stalled handlers, like a provider recovering
                waited = 0.0
                while waited < stall_s and self._mode_still(gen):
                    time.sleep(0.05)
                    waited += 0.05
                if waited >= stall_s:
                    # never answered — the client's timeout fires
                    return
            payload, sent = self._respond(handler, status, retry_after,
                                          mode, body, prompt)
            self._log_request(handler, prompt, sent, t_in)
        finally:
            with self._lock:
                self._inflight -= 1

    def _respond(self, handler, status, retry_after, mode, body,
                 prompt):
        if status != 200:
            payload = json.dumps(
                {'error': {'type': {429: 'rate_limited',
                                    500: 'server_error',
                                    401: 'auth'}[status],
                           'message': f'injected {status}'}}).encode()
            handler.send_response(status)
            if status == 429 and retry_after is not None:
                handler.send_header('Retry-After', str(retry_after))
            handler.send_header('Content-Type', 'application/json')
            handler.send_header('Content-Length', str(len(payload)))
            handler.end_headers()
            handler.wfile.write(payload)
            return None, status
        if mode == 'malformed':
            with self._lock:
                self._counters['malformed'] += 1
            payload = b'{"choices": [ {"truncated'
            handler.send_response(200)
            handler.send_header('Content-Type', 'application/json')
            handler.send_header('Content-Length', str(len(payload)))
            handler.end_headers()
            handler.wfile.write(payload)
            return None, 200
        with self._lock:
            self._counters['ok'] += 1
        text = canned_text(prompt)
        if isinstance(body.get('messages'), list):
            out = {'choices': [{'message': {'content': text}}]}
        elif body.get('echo'):
            # CompletionsAPI.get_ppl: deterministic echoed logprobs
            n = max(len(str(prompt).split()), 1)
            out = {'choices': [{'logprobs': {'token_logprobs':
                   [None] + [-1.0] * min(n, 8)}}]}
        else:
            out = {'choices': [{'text': text}]}
        payload = json.dumps(out).encode()
        handler.send_response(200)
        handler.send_header('Content-Type', 'application/json')
        handler.send_header('Content-Length', str(len(payload)))
        handler.end_headers()
        handler.wfile.write(payload)
        return out, 200

    def _log_request(self, handler, prompt, status, t_in):
        with self._lock:
            self._log.append({
                't': t_in,
                'status': status,
                'prompt': str(prompt)[:120],
                'deadline_ms':
                    handler.headers.get('X-OCT-Deadline-Ms'),
            })
