# oct-lint: clock-discipline
"""Concurrency + pacing control for outbound API traffic.

Two cooperating limiters replace the old busy-thread QPS
``TokenBucket``:

- :class:`AimdLimiter` bounds **concurrent in-flight requests** with
  TCP-style additive-increase / multiplicative-decrease: a 429 or 5xx
  halves the window (at most once per ``hold_s`` so one burst of
  concurrent throttles costs one decrease, not a collapse to the
  floor), and every success re-probes upward by ``1/limit`` — the
  window converges near what the provider actually sustains instead of
  what the config guessed.
- :class:`Pacer` spaces **request launches** — an optional steady QPS
  interval plus a global ``Retry-After`` gate: when the provider says
  "come back in N seconds", *every* worker honors it, instead of each
  thread discovering the 429 for itself.

Both are lock-guarded and clock-injected (``now=``); the scheduler's
tests drive them deterministically.
"""
from __future__ import annotations

import threading
import time
from typing import Dict, Optional

DEFAULT_MAX_INFLIGHT = 8
DEFAULT_HOLD_S = 1.0


class AimdLimiter:
    """Adaptive bound on concurrent in-flight requests.

    ``acquire``/``release`` bracket one request; ``on_throttle``
    (429/5xx) halves the window, ``on_success`` creeps it back up.
    The *low-water* mark records how far the provider pushed us down —
    the chaos harness's "pacing adapted" evidence."""

    def __init__(self, max_limit: int = DEFAULT_MAX_INFLIGHT,
                 min_limit: int = 1, backoff: float = 0.5,
                 hold_s: float = DEFAULT_HOLD_S):
        self.max_limit = max(int(max_limit), 1)
        self.min_limit = max(int(min_limit), 1)
        self.backoff = float(backoff)
        self.hold_s = float(hold_s)
        self._cond = threading.Condition()
        # guarded-by: _cond
        self._limit = float(self.max_limit)
        # guarded-by: _cond
        self._inflight = 0
        # guarded-by: _cond
        self._last_decrease_ts: Optional[float] = None
        # guarded-by: _cond
        self._low_water = float(self.max_limit)
        # guarded-by: _cond
        self._throttles = 0

    def acquire(self, timeout: Optional[float] = None) -> bool:
        """Block until an in-flight slot is free (or ``timeout``
        expires — returns False; the caller maps that to a deadline
        failure, never a silent skip)."""
        with self._cond:
            granted = self._cond.wait_for(
                lambda: self._inflight < max(int(self._limit),
                                             self.min_limit),
                timeout=timeout)
            if granted:
                self._inflight += 1
            return granted

    def release(self):
        with self._cond:
            self._inflight = max(0, self._inflight - 1)
            self._cond.notify_all()

    def on_success(self):
        """Additive increase: one success grows the window by
        ``1/limit`` (one full window of successes ≈ +1 slot)."""
        with self._cond:
            if self._limit < self.max_limit:
                self._limit = min(self.max_limit,
                                  self._limit + 1.0 / max(self._limit,
                                                          1.0))
                self._cond.notify_all()

    def on_throttle(self, now: Optional[float] = None):
        """Multiplicative decrease, at most once per ``hold_s`` — N
        concurrent requests all seeing the same 429 burst must cost
        one halving, not ``backoff**N``."""
        now = time.monotonic() if now is None else float(now)
        with self._cond:
            self._throttles += 1
            last = self._last_decrease_ts
            if last is not None and now - last < self.hold_s:
                return
            self._last_decrease_ts = now
            self._limit = max(float(self.min_limit),
                              self._limit * self.backoff)
            self._low_water = min(self._low_water, self._limit)

    def snapshot(self) -> Dict:
        with self._cond:
            return {'limit': round(self._limit, 2),
                    'inflight': self._inflight,
                    'max_limit': self.max_limit,
                    'low_water': round(self._low_water, 2),
                    'throttles': self._throttles}


class Pacer:
    """Launch spacing: optional steady QPS interval + a global
    ``Retry-After`` hold.

    ``reserve`` hands the caller its launch slot as a *delay to sleep*
    (0 when clear) and advances the shared schedule, so concurrent
    workers space themselves without a dedicated feeder thread — this
    is the clock-disciplined replacement for the old busy-thread
    ``TokenBucket`` refill loop."""

    def __init__(self, qps: Optional[float] = None):
        self._interval = 1.0 / float(qps) if qps else 0.0
        self._lock = threading.Lock()
        # guarded-by: _lock
        self._next_free: Optional[float] = None
        # guarded-by: _lock
        self._not_before: Optional[float] = None
        # guarded-by: _lock
        self._holds = 0

    def reserve(self, now: Optional[float] = None) -> float:
        """Claim the next launch slot; returns seconds the caller must
        sleep before sending (0.0 = go now)."""
        now = time.monotonic() if now is None else float(now)
        with self._lock:
            base = now
            if self._next_free is not None:
                base = max(base, self._next_free)
            if self._not_before is not None:
                base = max(base, self._not_before)
            self._next_free = base + self._interval
            return max(0.0, base - now)

    def hold(self, seconds: float, now: Optional[float] = None):
        """Provider-directed pause (``Retry-After``): nothing launches
        for ``seconds``.  Holds only ever extend the gate — two 429s
        racing each other keep the later horizon."""
        now = time.monotonic() if now is None else float(now)
        gate = now + max(float(seconds), 0.0)
        with self._lock:
            self._holds += 1
            if self._not_before is None or gate > self._not_before:
                self._not_before = gate

    def snapshot(self, now: Optional[float] = None) -> Dict:
        now = time.monotonic() if now is None else float(now)
        with self._lock:
            hold_s = 0.0
            if self._not_before is not None:
                hold_s = max(0.0, self._not_before - now)
            return {'interval_s': self._interval,
                    'hold_remaining_s': round(hold_s, 3),
                    'holds': self._holds}
