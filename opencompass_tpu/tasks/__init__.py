from .base import BaseTask  # noqa
from .llm_eval import ModelEvaluator  # noqa
from .openicl_infer import OpenICLInferTask  # noqa
from .openicl_eval import OpenICLEvalTask  # noqa

__all__ = ['BaseTask', 'ModelEvaluator', 'OpenICLInferTask',
           'OpenICLEvalTask']
