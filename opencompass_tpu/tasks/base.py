"""Task layer: a task is an independently-runnable unit of (models × datasets)
work, re-invokable as a standalone script — the process boundary that makes
runners trivial (SURVEY.md §2.1; parity: reference tasks/base.py:10-87).

Output-file existence is the completion criterion runners/partitioners key
on (reference abbr.py:38-46 protocol).
"""
from __future__ import annotations

import os.path as osp
from typing import Dict, List

from opencompass_tpu.config import Config
from opencompass_tpu.utils.abbr import (dataset_abbr_from_cfg,
                                        get_infer_output_path,
                                        model_abbr_from_cfg,
                                        task_abbr_from_cfg)


class BaseTask:
    """Base class for tasks.

    Args:
        cfg: the task config — a full run config narrowed to this task's
            ``models`` (list) and ``datasets`` (list-of-lists, one inner list
            per model).
    """

    name_prefix: str = ''
    log_subdir: str = ''
    output_subdir: str = ''

    def __init__(self, cfg: Dict):
        cfg = Config(cfg) if not isinstance(cfg, Config) else cfg
        self.cfg = cfg
        self.model_cfgs = cfg['models']
        self.dataset_cfgs = cfg['datasets']
        self.work_dir = cfg.get('work_dir', './outputs/default')
        run_cfgs = [m.get('run_cfg', {}) for m in self.model_cfgs]
        self.num_devices = max(
            (rc.get('num_devices', rc.get('num_gpus', 0))
             for rc in run_cfgs), default=0)
        self.num_procs = max(
            (rc.get('num_procs', 1) for rc in run_cfgs), default=1)

    @property
    def name(self) -> str:
        return self.name_prefix + task_abbr_from_cfg(
            {'models': self.model_cfgs, 'datasets': self.dataset_cfgs})

    def __repr__(self):
        return f'{type(self).__name__}({self.name})'

    def get_log_path(self, file_extension: str = 'out') -> str:
        """Log path keyed to the task's first model/dataset pair."""
        return osp.join(
            self.work_dir, self.log_subdir,
            model_abbr_from_cfg(self.model_cfgs[0]),
            f'{dataset_abbr_from_cfg(self.dataset_cfgs[0][0])}.'
            f'{file_extension}')

    def get_output_paths(self, file_extension: str = 'json') -> List[str]:
        """Every output file this task is expected to produce; their
        existence is how runners decide success/skip."""
        paths = []
        for i, model in enumerate(self.model_cfgs):
            for dataset in self.dataset_cfgs[i]:
                paths.append(
                    get_infer_output_path(
                        model, dataset,
                        osp.join(self.work_dir, self.output_subdir),
                        file_extension))
        return paths

    def get_command(self, cfg_path: str, template: str) -> str:
        """Shell command to run this task out-of-process.

        ``template`` contains ``{task_cmd}``, e.g. ``"{task_cmd}"`` or a
        wrapper like ``srun ... {task_cmd}``.
        """
        raise NotImplementedError

    def run(self):
        raise NotImplementedError
