"""Task launcher: ``python -m opencompass_tpu.tasks <TaskType> <cfg.py>``.

A single entry point avoids the runpy double-import trap (running a task
module directly via ``-m`` would execute it twice: once as a package import,
once as ``__main__``, re-registering its class).
"""
import argparse
import os
import time

from opencompass_tpu import obs
from opencompass_tpu.config import Config
from opencompass_tpu.parallel.distributed import init_from_env, shutdown
from opencompass_tpu.registry import TASKS
from opencompass_tpu.utils.logging import get_logger


def main():
    parser = argparse.ArgumentParser(description='Run a task standalone')
    parser.add_argument('task_type', help='registered task class name')
    parser.add_argument('config', help='task config file path')
    args = parser.parse_args()

    logger = get_logger()
    init_from_env()  # join the multi-host group before touching devices
    cls = TASKS.get(args.task_type)
    if cls is None:
        raise SystemExit(f'unknown task type {args.task_type!r}')
    cfg = Config.fromfile(args.config)
    # persistent XLA compilation cache: resolve from the driver-exported
    # env (OCT_CACHE_ROOT / JAX_COMPILATION_CACHE_DIR) or, for a task
    # launched standalone, this task's own work_dir — a resumed/retried
    # task then deserializes the previous attempt's executables instead
    # of recompiling (utils/compile_cache.py)
    from opencompass_tpu.utils import compile_cache
    compile_cache.export_env(cfg.get('work_dir'))
    compile_cache.enable(cfg.get('work_dir'))
    # resume the run's trace across the process boundary (OCT_* env vars
    # injected by the runner; no-op when the run is not traced)
    tracer = obs.init_task_obs(cfg)
    task = cls(cfg)
    # live progress file for the driver's status aggregator / stall
    # watchdog (NoopHeartbeat when the run is untraced)
    heartbeat = obs.init_task_heartbeat(task.name)
    # per-batch flight recorder ({obs_dir}/timeline/<task>.jsonl;
    # NoopTimeline when the run is untraced)
    obs.init_task_timeline(task.name)
    logger.info(f'Task {task.name}')
    start = time.time()
    try:
        with tracer.span(f'proc:{args.task_type}', task=task.name,
                         pid=os.getpid()):
            try:
                task.run()
            finally:
                shutdown()
        heartbeat.mark('done')
    except BaseException:
        heartbeat.mark('failed')
        raise
    finally:
        tracer.close()
    logger.info(f'time elapsed: {time.time() - start:.2f}s')


if __name__ == '__main__':
    main()
