"""Multi-process task launcher — the ``torchrun`` analog.

``python -m opencompass_tpu.tasks.launch --nprocs N [--] cmd args...``
spawns ``cmd`` N times with the OC_* process-group environment
(parallel/distributed.py contract) pointing at a local coordinator, streams
each child's output with a ``[pK]`` prefix, and exits non-zero if any child
fails.  Reference equivalent: the ``torchrun --master_port=rand
--nproc_per_node {num_procs}`` command template
(reference tasks/openicl_infer.py:34-40).

On a single machine this emulates N hosts (each child sees only its local
devices plus the process group); on a real cluster the scheduler sets the
OC_*/SLURM_* variables instead and this wrapper is unnecessary.
"""
from __future__ import annotations

import argparse
import os
import socket
import subprocess
import sys
import threading
import time


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(('127.0.0.1', 0))
        return s.getsockname()[1]


def _stream(proc: subprocess.Popen, rank: int):
    for line in proc.stdout:
        sys.stdout.write(f'[p{rank}] {line}')
        sys.stdout.flush()


def _chip_partition(nprocs: int):
    """Per-rank TPU chip assignments for local emulation, or None.

    Local children would otherwise all try to claim every chip.  Honors an
    existing TPU_VISIBLE_CHIPS set by the runner's slot allocator.  When
    chips can't be split evenly (e.g. a single chip shared by 2 procs),
    returns None — callers should run such groups on CPU devices
    (JAX_PLATFORMS=cpu) or one-process-per-host where the scheduler owns
    device visibility.
    """
    if os.environ.get('JAX_PLATFORMS', '').startswith('cpu'):
        return None  # CPU devices are per-process anyway
    chips = os.environ.get('TPU_VISIBLE_CHIPS')
    if not chips:
        return None
    ids = [c for c in chips.split(',') if c]
    if len(ids) % nprocs:
        return None
    per = len(ids) // nprocs
    return [','.join(ids[r * per:(r + 1) * per]) for r in range(nprocs)]


def launch(nprocs: int, cmd: list, port: int = 0) -> int:
    port = port or _free_port()
    chip_split = _chip_partition(nprocs)
    if (chip_split is None
            and not os.environ.get('JAX_PLATFORMS', '').startswith('cpu')
            and os.environ.get('TPU_VISIBLE_CHIPS')):
        sys.stderr.write(
            'launch: TPU_VISIBLE_CHIPS not divisible by nprocs; children '
            'may contend for chips\n')
    procs, threads = [], []
    for rank in range(nprocs):
        env = dict(os.environ)
        env['OC_COORDINATOR'] = f'127.0.0.1:{port}'
        env['OC_NUM_PROCESSES'] = str(nprocs)
        env['OC_PROCESS_ID'] = str(rank)
        env['JAX_PROCESS_INDEX'] = str(rank)
        if chip_split is not None:
            env['TPU_VISIBLE_CHIPS'] = chip_split[rank]
        proc = subprocess.Popen(cmd, env=env, text=True,
                                stdout=subprocess.PIPE,
                                stderr=subprocess.STDOUT)
        procs.append(proc)
        t = threading.Thread(target=_stream, args=(proc, rank), daemon=True)
        t.start()
        threads.append(t)

    # fail fast: one dead rank leaves the rest blocked in collectives, so
    # kill the survivors instead of hanging until a distributed timeout
    rc = 0
    live = list(procs)
    while live:
        for proc in list(live):
            code = proc.poll()
            if code is None:
                continue
            live.remove(proc)
            rc = rc or code
            if code != 0:
                for other in live:
                    other.terminate()
        time.sleep(0.2)
    for t in threads:
        t.join(timeout=5)
    return rc


def main():
    parser = argparse.ArgumentParser(
        description='Launch a command as an N-process JAX group')
    parser.add_argument('--nprocs', type=int, required=True)
    parser.add_argument('--port', type=int, default=0,
                        help='coordinator port (default: pick a free one)')
    parser.add_argument('cmd', nargs=argparse.REMAINDER,
                        help='command to run per process')
    args = parser.parse_args()
    cmd = args.cmd
    if cmd and cmd[0] == '--':
        cmd = cmd[1:]
    if not cmd:
        raise SystemExit('no command given')
    raise SystemExit(launch(args.nprocs, cmd, args.port))


if __name__ == '__main__':
    main()
