"""LLM-judge model comparison (finished version of the reference's stub).

Reference tasks/llm_eval.py:11-91 sketches a ``ModelEvaluator`` that ranks
several models' responses per question with a judge LLM ("sort the answers,
reply digits") but is marked ``TODO: Finish the implementation`` and has
index/score bugs.  This implementation completes the design:

- loads each model's predictions JSON from the standard output layout
  (``{work_dir}/predictions/{model_abbr}/{dataset_abbr}.json``),
- asks the judge to order the (shuffled, to fight position bias) answers
  from least to most appropriate,
- parses rankings robustly (digit extraction, length/permutation checks;
  malformed judgments are skipped and counted),
- aggregates Borda-style points per model and writes
  ``{work_dir}/results/llm_judge/{dataset_abbr}.json``.
"""
from __future__ import annotations

import json
import os
import os.path as osp
import random
import re
from collections import defaultdict
from typing import Dict, List, Optional

from opencompass_tpu.registry import EVALUATORS, MODELS
from opencompass_tpu.utils.abbr import (dataset_abbr_from_cfg,
                                        model_abbr_from_cfg)
from opencompass_tpu.utils.logging import get_logger

logger = get_logger()

_PROMPT = (
    'Below are a question and a set of answers, each numbered by a digit. '
    'Sort the answers from least to most appropriate to the question. '
    'Reply with only the digits separated by spaces, worst first. For '
    'example, with three answers, reply "1 0 2" when answer 0 is best '
    'and answer 1 is worst.\n'
    'Q: {question}\n')


@EVALUATORS.register_module()
class ModelEvaluator:
    """Args:
        config: dict with ``models`` (≥2 model cfgs whose predictions are
            compared), ``datasets``, ``work_dir``, and ``evaluator`` =
            dict(judger=<model cfg or instance>, max_out_len=...).
    """

    def __init__(self, config: Dict):
        self.cfg = config
        evaluator_cfg = dict(config.get('evaluator', {}))
        judger = evaluator_cfg.get('judger')
        if isinstance(judger, dict):
            judger = MODELS.build(judger)
        if judger is None:
            raise ValueError('ModelEvaluator needs evaluator.judger')
        self.judger = judger
        self.max_out_len = evaluator_cfg.get('max_out_len', 16)
        self.seed = evaluator_cfg.get('seed', 0)
        self.work_dir = config.get('work_dir', '.')
        self.dataset_abbrs = [dataset_abbr_from_cfg(d)
                              for d in config['datasets']]
        self.model_abbrs = [model_abbr_from_cfg(m)
                            for m in config['models']]
        if len(self.model_abbrs) < 2:
            raise ValueError('need at least two models to compare')

    # -- per-dataset --------------------------------------------------------

    def _load_predictions(self, dataset_abbr: str) -> Optional[List]:
        """[(question, [resp_model0, resp_model1, ...]), ...] — list, not a
        dict keyed by prompt: duplicate questions must not collapse."""
        per_model = []
        for model_abbr in self.model_abbrs:
            path = osp.join(self.work_dir, 'predictions', model_abbr,
                            f'{dataset_abbr}.json')
            if not osp.exists(path):
                logger.warning(f'missing predictions: {path}')
                return None
            with open(path) as f:
                per_model.append(json.load(f))
        keys = [k for k in per_model[0]
                if all(k in preds for preds in per_model)]
        return [
            (per_model[0][key]['origin_prompt'],
             [preds[key]['prediction'] for preds in per_model])
            for key in keys
        ]

    def _parse_ranking(self, output: str, n: int) -> Optional[List[int]]:
        digits = [int(d) for d in re.findall(r'\d+', str(output))]
        if len(digits) < n or sorted(digits[:n]) != list(range(n)):
            return None
        return digits[:n]

    def _evaluate_dataset(self, dataset_abbr: str) -> Optional[Dict]:
        data = self._load_predictions(dataset_abbr)
        if data is None:
            return None
        rng = random.Random(self.seed)
        scores = defaultdict(float)
        judged = skipped = 0
        n = len(self.model_abbrs)
        # build every judge prompt up front: one batched generate() call
        # lets API judges fan out over their thread pool instead of paying
        # one serial round-trip per question
        orders, prompts = [], []
        for question, responses in data:
            order = list(range(n))
            rng.shuffle(order)  # shuffle to fight judge position bias
            prompt = _PROMPT.format(question=question)
            for pos, model_idx in enumerate(order):
                prompt += f'A{pos}: {responses[model_idx]}\n'
            orders.append(order)
            prompts.append(prompt)
        outputs = self.judger.generate(prompts,
                                       max_out_len=self.max_out_len)
        for order, output in zip(orders, outputs):
            ranking = self._parse_ranking(output, n)
            if ranking is None:
                skipped += 1
                continue
            judged += 1
            # Borda points: position in the worst→best list = points
            for points, pos in enumerate(ranking):
                scores[self.model_abbrs[order[pos]]] += points
        if not judged:
            logger.warning(f'{dataset_abbr}: no parseable judgments')
            return None
        max_points = (n - 1) * judged or 1
        return {
            'scores': {m: round(s / max_points * 100, 2)
                       for m, s in scores.items()},
            'judged': judged,
            'skipped': skipped,
        }

    # -- entry --------------------------------------------------------------

    def evaluate(self) -> Dict[str, Dict]:
        results = {}
        out_dir = osp.join(self.work_dir, 'results', 'llm_judge')
        os.makedirs(out_dir, exist_ok=True)
        for dataset_abbr in self.dataset_abbrs:
            result = self._evaluate_dataset(dataset_abbr)
            if result is None:
                continue
            results[dataset_abbr] = result
            # completion-keyed output: resume skips datasets whose file
            # exists, so the write must be atomic (no torn half-result)
            from opencompass_tpu.utils.fileio import atomic_write_json
            atomic_write_json(osp.join(out_dir, f'{dataset_abbr}.json'),
                              result, dump_kwargs={'indent': 2})
            logger.info(f'{dataset_abbr} judge scores: {result["scores"]}')
        return results
