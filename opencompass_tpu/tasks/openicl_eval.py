"""Evaluation task: load predictions, postprocess, score vs references.

CPU-only (``num_devices = 0``) — scoring never touches the accelerator.
Handles partial prediction shards ``_0.json, _1.json, ...`` produced by
size-partitioned infer tasks.  Runnable standalone, same as the infer task.
Parity: reference tasks/openicl_eval.py:17-178.
"""
from __future__ import annotations

import json
import os.path as osp
from typing import Dict, List, Optional

from opencompass_tpu.obs import get_heartbeat, get_tracer
from opencompass_tpu.registry import (ICL_EVALUATORS, TASKS,
                                      TEXT_POSTPROCESSORS)
from opencompass_tpu.utils.abbr import (dataset_abbr_from_cfg,
                                        get_infer_output_path,
                                        model_abbr_from_cfg)
from opencompass_tpu.utils.build import build_dataset_from_cfg
from opencompass_tpu.utils.logging import get_logger

from .base import BaseTask

logger = get_logger()


def _postprocessor_from_cfg(cfg: Dict):
    """(callable, kwargs) from ``dict(type='name'|callable, **kwargs)``."""
    cfg = dict(cfg)
    proc = cfg.pop('type')
    if isinstance(proc, str):
        resolved = TEXT_POSTPROCESSORS.get(proc)
        if resolved is None:
            raise KeyError(f'unknown text postprocessor {proc!r}')
        proc = resolved
    return proc, cfg


def extract_role_pred(s: str, begin_str: Optional[str],
                      end_str: Optional[str]) -> str:
    """Extract the model's own turn from a raw completion: text after the
    first ``begin_str`` and before the next ``end_str`` (parity: reference
    openicl_eval.py:133-161)."""
    start = 0
    end = len(s)
    if begin_str:
        begin_idx = s.find(begin_str)
        if begin_idx != -1:
            start = begin_idx + len(begin_str)
    if end_str:
        end_idx = s.find(end_str, start)
        if end_idx != -1:
            end = end_idx
    return s[start:end]


@TASKS.register_module()
class OpenICLEvalTask(BaseTask):

    name_prefix = 'OpenICLEval'
    log_subdir = 'logs/eval'
    output_subdir = 'results'

    def __init__(self, cfg):
        super().__init__(cfg)
        self.num_devices = 0

    def get_command(self, cfg_path: str,
                    template: str = '{task_cmd}') -> str:
        task_cmd = ('python -m opencompass_tpu.tasks OpenICLEvalTask '
                    f'{cfg_path}')
        return template.format(task_cmd=task_cmd)

    def run(self):
        tracer = get_tracer()
        heartbeat = get_heartbeat()
        units_total = sum(len(d) for d in self.dataset_cfgs)
        units_done = 0
        for i, model_cfg in enumerate(self.model_cfgs):
            for dataset_cfg in self.dataset_cfgs[i]:
                self.model_cfg = model_cfg
                self.dataset_cfg = dataset_cfg
                self.eval_cfg = dataset_cfg.get('eval_cfg', {})
                self.output_column = dataset_cfg['reader_cfg'][
                    'output_column']
                m_abbr = model_abbr_from_cfg(model_cfg)
                d_abbr = dataset_abbr_from_cfg(dataset_cfg)
                out_path = get_infer_output_path(
                    model_cfg, dataset_cfg,
                    osp.join(self.work_dir, 'results'))
                # resume mirror of the infer side: skip only when the
                # result is at least as new as its predictions — a
                # re-inferred (or store-materialized) prediction file
                # must be re-scored, not shadowed by a stale result
                if osp.exists(out_path) and self._result_fresh(out_path):
                    tracer.event('eval_skipped', model=m_abbr,
                                 dataset=d_abbr)
                    units_done += 1
                    heartbeat.set_unit(units_done, units_total)
                    continue
                heartbeat.set_unit(units_done, units_total,
                                   f'{m_abbr}/{d_abbr}')
                with tracer.span(f'eval:{m_abbr}/{d_abbr}') as span:
                    self._score(out_path)
                    span.set_attrs(scored=osp.exists(out_path))
                units_done += 1
                heartbeat.set_unit(units_done, units_total)

    def _prediction_paths(self) -> List[str]:
        """Existing prediction file(s) for the current pair: the whole
        file, or its ``_k`` shards from a size-partitioned run."""
        filename = get_infer_output_path(
            self.model_cfg, self.dataset_cfg,
            osp.join(self.work_dir, 'predictions'))
        if osp.exists(filename):
            return [filename]
        root, ext = osp.splitext(filename)
        paths = []
        i = 0
        while osp.exists(f'{root}_{i}{ext}'):
            paths.append(f'{root}_{i}{ext}')
            i += 1
        return paths

    def _result_fresh(self, out_path: str) -> bool:
        """Is the existing result at least as new as every prediction
        file it scored?  Vacuously fresh with no predictions (nothing
        to rescore)."""
        try:
            result_mtime = osp.getmtime(out_path)
            return all(osp.getmtime(p) <= result_mtime
                       for p in self._prediction_paths())
        except OSError:
            return False   # raced file: re-evaluate to be safe

    def _load_predictions(self) -> Optional[List[Dict]]:
        """Prediction records in index order, stitching `_k` shards."""
        records = []
        for path in self._prediction_paths():
            with open(path) as f:
                sub = json.load(f)
            records.extend(sub[str(k)] for k in range(len(sub)))
        return records or None

    def _score(self, out_path: str):
        records = self._load_predictions()
        if not records:
            logger.error(f'No predictions found for {self.dataset_cfg} — '
                         'did the infer task run?')
            return
        pred_strs = [rec.get('prediction') for rec in records]

        if self.eval_cfg.get('pred_role') and 'meta_template' in \
                self.model_cfg:
            role_cfg = None
            meta = self.model_cfg['meta_template']
            for item in meta.get('round', []):
                if isinstance(item, dict) \
                        and item.get('role') == self.eval_cfg['pred_role']:
                    role_cfg = item
            if role_cfg is not None:
                pred_strs = [
                    extract_role_pred(str(s), role_cfg.get('begin'),
                                      role_cfg.get('end'))
                    for s in pred_strs
                ]

        if 'pred_postprocessor' in self.eval_cfg:
            proc, kwargs = _postprocessor_from_cfg(
                self.eval_cfg['pred_postprocessor'])
            pred_strs = [proc(str(s), **kwargs) for s in pred_strs]

        dataset = build_dataset_from_cfg(self.dataset_cfg)
        references = dataset.test[self.output_column] \
            if self.output_column else None
        # size-split tasks carry a test_range slice in reader_cfg, which
        # build_dataset_from_cfg already applied; references align 1:1
        if 'dataset_postprocessor' in self.eval_cfg and references:
            proc, kwargs = _postprocessor_from_cfg(
                self.eval_cfg['dataset_postprocessor'])
            references = [proc(str(r), **kwargs) for r in references]

        evaluator_cfg = dict(self.eval_cfg.get(
            'evaluator', {'type': 'AccEvaluator'}))
        evaluator = ICL_EVALUATORS.build(evaluator_cfg)
        result = evaluator.score(predictions=pred_strs,
                                 references=references)

        if 'error' in result:
            logger.error(
                f'Task {self.name}: {result["error"]}')
            return
        logger.info(f'Task {self.name}: {result}')

        # completion-keyed output (resume checks file existence): atomic
        # write, byte-identical serialization to the old open('w') path
        from opencompass_tpu.utils.fileio import atomic_write_json
        atomic_write_json(out_path, result,
                          dump_kwargs={'ensure_ascii': False, 'indent': 4})
