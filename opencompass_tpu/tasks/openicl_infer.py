"""Inference task: for each (model, dataset) pair, run the ICL pipeline
(retriever → templates → inferencer) and write predictions JSON.

Runnable standalone (``python -m opencompass_tpu.tasks OpenICLInferTask
cfg.py``) — the runner re-invokes it across the process boundary
(parity: reference tasks/openicl_infer.py:17-129).  TPU difference: no
``torchrun`` wrapper — multi-device execution happens *inside* the process
via the model's mesh (pjit shardings), so the command is always plain
``python`` and the runner instead pins visible devices via env.
"""
from __future__ import annotations

import os.path as osp
from typing import Any, Dict

from opencompass_tpu.obs import (device_memory_attrs, get_heartbeat,
                                 get_timeline, get_tracer)
from opencompass_tpu.parallel.distributed import (broadcast_object,
                                                  is_main_process)
from opencompass_tpu.registry import (ICL_INFERENCERS, ICL_PROMPT_TEMPLATES,
                                      ICL_RETRIEVERS, TASKS)
from opencompass_tpu.utils.abbr import (dataset_abbr_from_cfg,
                                        get_infer_output_path,
                                        model_abbr_from_cfg)
from opencompass_tpu.utils.build import (build_dataset_from_cfg,
                                         build_model_from_cfg)
from opencompass_tpu.utils.logging import get_logger
from opencompass_tpu.utils.perf import TaskProfiler

from .base import BaseTask

logger = get_logger()


@TASKS.register_module()
class OpenICLInferTask(BaseTask):

    name_prefix = 'OpenICLInfer'
    log_subdir = 'logs/infer'
    output_subdir = 'predictions'

    def get_command(self, cfg_path: str,
                    template: str = '{task_cmd}') -> str:
        task_cmd = ('python -m opencompass_tpu.tasks OpenICLInferTask '
                    f'{cfg_path}')
        if self.num_procs > 1:
            # multi-host process group (the reference's `torchrun
            # --nproc_per_node` analog; one process per host on real pods)
            task_cmd = (f'python -m opencompass_tpu.tasks.launch '
                        f'--nprocs {self.num_procs} -- {task_cmd}')
        return template.format(task_cmd=task_cmd)

    def run(self):
        tracer = get_tracer()
        heartbeat = get_heartbeat()
        units_total = sum(len(d) for d in self.dataset_cfgs)
        units_done = 0
        for i, model_cfg in enumerate(self.model_cfgs):
            self.max_out_len = model_cfg.get('max_out_len')
            self.batch_size = model_cfg.get('batch_size', 1)
            self.max_seq_len = model_cfg.get('max_seq_len')
            model = build_model_from_cfg(model_cfg)
            # heartbeat writes report live tokens/s off the model's
            # perf counters
            heartbeat.bind_perf(getattr(model, 'perf', None))
            # content-addressed result store: inferencers serve cached
            # rows from disk and commit fresh ones as batches complete
            # (no-op when disabled / no cache root / API model).  A
            # serve-mode sweep carries the engine's cache_root, so the
            # binding is engine-owned — this task commits to the
            # daemon's store no matter which work_dir it runs under
            from opencompass_tpu import store as result_store
            result_store.bind_model_store(model, model_cfg, self.cfg,
                                          work_dir=self.work_dir,
                                          root=self.cfg.get('cache_root'))

            try:
                self._infer_model_datasets(
                    model, model_cfg, i, tracer, heartbeat,
                    units_done, units_total)
            finally:
                # persist the token-length cache even on failure: the
                # retry/resume attempt skips re-tokenizing what this
                # attempt already measured
                try:
                    model.save_caches()
                except Exception:
                    logger.warning('model cache persistence failed',
                                   exc_info=True)
            units_done += len(self.dataset_cfgs[i])

    def _infer_model_datasets(self, model, model_cfg, i, tracer,
                              heartbeat, units_done, units_total):
        for dataset_cfg in self.dataset_cfgs[i]:
            self.model_cfg = model_cfg
            self.dataset_cfg = dataset_cfg
            self.infer_cfg = dataset_cfg['infer_cfg']
            m_abbr = model_abbr_from_cfg(model_cfg)
            d_abbr = dataset_abbr_from_cfg(dataset_cfg)
            out_path = get_infer_output_path(
                model_cfg, dataset_cfg,
                osp.join(self.work_dir, 'predictions'))
            # rank 0 owns the filesystem view; broadcast so a
            # multi-host group takes the same skip decision
            if broadcast_object(osp.exists(out_path)
                                if is_main_process() else None):
                tracer.event('infer_skip', model=m_abbr,
                             dataset=d_abbr)
                # seed the unit store from pre-existing outputs too, so
                # legacy --reuse runs feed cross-run pruning
                self._record_unit(model, model_cfg, dataset_cfg,
                                  out_path)
                units_done += 1
                heartbeat.set_unit(units_done, units_total)
                continue
            heartbeat.set_unit(units_done, units_total,
                               f'{m_abbr}/{d_abbr}')
            # flight-recorder batches attribute to this unit
            get_timeline().set_unit(f'{m_abbr}/{d_abbr}')
            perf_path = trace_dir = None
            if is_main_process():
                perf_path = get_infer_output_path(
                    model_cfg, dataset_cfg,
                    osp.join(self.work_dir, 'perf'))
                if self.cfg.get('profile'):
                    trace_dir = osp.join(
                        self.work_dir, 'profile', m_abbr, d_abbr)
            with tracer.span(f'infer:{m_abbr}/{d_abbr}') as span:
                prof = TaskProfiler(model, perf_path, trace_dir)
                try:
                    with prof:
                        self._inference(model, out_path)
                finally:
                    # attach even when _inference raised: the failed
                    # task's compile/device time must reach the trace
                    # report (TaskProfiler.__exit__ always builds the
                    # record, with 'error' on failure)
                    if prof.record:
                        # the span-local counter backend: the trace
                        # report reads compile/device attribution here
                        span.set_attrs(perf=prof.record)
                    if tracer.enabled:
                        mem = device_memory_attrs()
                        if mem:
                            span.set_attrs(device_memory=mem)
                            if 'peak_bytes_in_use' in mem:
                                tracer.gauge(
                                    'device.peak_bytes_in_use').set(
                                        mem['peak_bytes_in_use'])
            # whole-unit manifest for the partitioners' pre-launch
            # prune: an identical (model, dataset) pair in a future run
            # materializes its predictions without launching a task
            self._record_unit(model, model_cfg, dataset_cfg, out_path)
            units_done += 1
            heartbeat.set_unit(units_done, units_total)
            if prof.record and is_main_process():
                logger.info(
                    f'perf: {prof.record.get("samples_per_sec", "?")} '
                    f'samples/s, {prof.record.get("tokens_per_sec", "?")}'
                    f' tokens/s (wall {prof.record["wall_seconds"]}s)')

    @staticmethod
    def _record_unit(model, model_cfg, dataset_cfg, out_path: str):
        """Snapshot a completed prediction file into the unit store
        (rank 0, bound-store models only).  Never fails the task."""
        store = getattr(model, '_result_store', None)
        if store is None or not is_main_process() \
                or not osp.exists(out_path):
            return
        from opencompass_tpu.store import record_unit
        record_unit(store, model_cfg, dataset_cfg, out_path)

    def _inference(self, model, out_path: str):
        assert 'ice_template' in self.infer_cfg \
            or 'prompt_template' in self.infer_cfg, \
            'Both ice_template and prompt_template cannot be None ' \
            'simultaneously.'
        ice_template = None
        if 'ice_template' in self.infer_cfg:
            ice_template = ICL_PROMPT_TEMPLATES.build(
                self.infer_cfg['ice_template'])
        prompt_template = None
        if 'prompt_template' in self.infer_cfg:
            prompt_template = ICL_PROMPT_TEMPLATES.build(
                self.infer_cfg['prompt_template'])

        dataset = build_dataset_from_cfg(self.dataset_cfg)
        retriever_cfg = dict(self.infer_cfg['retriever'])
        retriever_cfg['dataset'] = dataset
        retriever = ICL_RETRIEVERS.build(retriever_cfg)

        inferencer_cfg = dict(self.infer_cfg['inferencer'])
        inferencer_cfg['model'] = model
        self._set_default(inferencer_cfg, 'max_out_len', self.max_out_len)
        self._set_default(inferencer_cfg, 'max_seq_len', self.max_seq_len)
        inferencer_cfg.setdefault('batch_size', self.batch_size)
        inferencer = ICL_INFERENCERS.build(inferencer_cfg)

        out_dir, out_file = osp.split(out_path)
        inferencer.inference(retriever,
                             ice_template=ice_template,
                             prompt_template=prompt_template,
                             output_json_filepath=out_dir,
                             output_json_filename=out_file)

    @staticmethod
    def _set_default(cfg: Dict[str, Any], key: str, value):
        if value is not None and key not in cfg:
            cfg[key] = value
