"""Component registries.

A :class:`Registry` maps string names to classes so configs can say
``dict(type='PPLInferencer', ...)`` (or pass the class object directly) and the
framework builds the component.  Replaces the reference's mmengine registries
(reference ``opencompass/registry.py:1-25``) with a dependency-free design that
supports lazy location scanning: modules listed in ``locations`` are only
imported on first lookup miss, keeping import time low.
"""
from __future__ import annotations

import importlib
import inspect
from typing import Any, Callable, Dict, List, Optional, Type


class Registry:

    def __init__(self, name: str, locations: Optional[List[str]] = None):
        self.name = name
        self._registry: Dict[str, Type] = {}
        self._locations = list(locations or [])
        self._scanned = False

    # -- registration -----------------------------------------------------
    def register_module(self,
                        name: Optional[str] = None,
                        module: Optional[Type] = None,
                        force: bool = False) -> Callable:
        """Register a class (decorator or direct call)."""
        if module is not None:
            self._register(module, name, force)
            return module

        def decorator(cls):
            self._register(cls, name, force)
            return cls

        return decorator

    def _register(self, cls: Type, name: Optional[str], force: bool):
        keys = [name] if isinstance(name, str) else (name or [cls.__name__])
        for key in keys:
            if not force and key in self._registry \
                    and self._registry[key] is not cls:
                raise KeyError(
                    f'{key} already registered in {self.name} registry')
            self._registry[key] = cls

    # -- lookup -----------------------------------------------------------
    def _scan_locations(self):
        if self._scanned:
            return
        self._scanned = True
        for loc in self._locations:
            mod = importlib.import_module(loc)
            # package locations register classes from their submodules
            # (e.g. every opencompass_tpu.datasets.<family> module)
            if hasattr(mod, '__path__'):
                import pkgutil
                for info in pkgutil.walk_packages(mod.__path__,
                                                  prefix=loc + '.'):
                    try:
                        importlib.import_module(info.name)
                    except ImportError as exc:  # optional-dep module
                        import logging
                        logging.getLogger('opencompass_tpu').warning(
                            f'registry scan skipped {info.name}: {exc}')

    def get(self, key: str) -> Optional[Type]:
        if key not in self._registry:
            self._scan_locations()
        if key not in self._registry and '.' in key:
            # Fully-qualified 'pkg.module.Class' escape hatch.
            mod_name, _, cls_name = key.rpartition('.')
            try:
                cls = getattr(importlib.import_module(mod_name), cls_name)
                self._registry[key] = cls
            except (ImportError, AttributeError):
                return None
        return self._registry.get(key)

    def __contains__(self, key: str) -> bool:
        return self.get(key) is not None

    def build(self, cfg: Dict[str, Any], **default_kwargs) -> Any:
        """Instantiate ``cfg['type']`` with the remaining keys as kwargs."""
        if not isinstance(cfg, dict) or 'type' not in cfg:
            raise TypeError(f'{self.name}: config must be a dict with a '
                            f'"type" key, got {cfg!r}')
        cfg = dict(cfg)
        obj_type = cfg.pop('type')
        if isinstance(obj_type, str):
            cls = self.get(obj_type)
            if cls is None:
                raise KeyError(f'{obj_type} is not registered in the '
                               f'{self.name} registry')
        elif inspect.isclass(obj_type) or callable(obj_type):
            cls = obj_type
        else:
            raise TypeError(f'type must be a str or class, got {obj_type!r}')
        kwargs = {**default_kwargs, **cfg}
        return cls(**kwargs)


_LOC = 'opencompass_tpu'

PARTITIONERS = Registry('partitioner', locations=[f'{_LOC}.partitioners'])
RUNNERS = Registry('runner', locations=[f'{_LOC}.runners'])
TASKS = Registry('task', locations=[f'{_LOC}.tasks'])
MODELS = Registry('model', locations=[f'{_LOC}.models'])
LOAD_DATASET = Registry('load_dataset', locations=[f'{_LOC}.datasets'])
TEXT_POSTPROCESSORS = Registry(
    'text_postprocessor',
    locations=[f'{_LOC}.utils.text_postprocessors', f'{_LOC}.datasets'])
EVALUATORS = Registry('evaluator', locations=[f'{_LOC}.icl.evaluators'])
ICL_INFERENCERS = Registry('icl_inferencer',
                           locations=[f'{_LOC}.icl.inferencers'])
ICL_RETRIEVERS = Registry('icl_retriever', locations=[f'{_LOC}.icl.retrievers'])
ICL_DATASET_READERS = Registry('icl_dataset_reader',
                               locations=[f'{_LOC}.icl.dataset_reader'])
ICL_PROMPT_TEMPLATES = Registry('icl_prompt_template',
                                locations=[f'{_LOC}.icl.prompt_template'])
ICL_EVALUATORS = Registry('icl_evaluator',
                          locations=[f'{_LOC}.icl.evaluators',
                                     f'{_LOC}.datasets'])
